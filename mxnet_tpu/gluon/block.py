"""Gluon Block / HybridBlock / SymbolBlock.

Parity target: `python/mxnet/gluon/block.py` — `Block` (:230, dynamic
imperative container), `HybridBlock` (:970, traces to CachedOp via
`_build_cache` :1067 / `hybridize` :1331), name scoping (`_BlockScope`),
child registration by attribute assignment, save/load_parameters.

TPU-native: `hybridize()` attaches a `mxnet_tpu.cached_op.CachedOp` that
jits the block's imperative forward into one XLA executable per input
signature (SURVEY §7.5 — "this is where TPU wins big"). Unhybridized blocks
run op-by-op through the eager executable cache.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

from ..cached_op import CachedOp, current_trace
from .parameter import (DeferredInitializationError, Parameter, ParameterDict)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name-scope manager (parity: gluon/block.py:35-120)."""

    _tls = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix and ParameterDict for a new Block."""
        current = getattr(_BlockScope._tls, "value", None)
        if current is None:
            if prefix is None:
                from .. import name as _name_mod

                prefix = _name_mod.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, shared=None)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._tls, "value", None)
        _BlockScope._tls.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._tls.value = self._old_scope


class Block:
    """Base container for layers & models (parity: gluon/block.py:230)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    # --------------------------------------------------------- registry ----
    def __setattr__(self, name, value):
        """Registers Parameters and child Blocks (parity: block.py:279)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(
                    value, type(existing)) and not isinstance(existing, type(value)):
                raise TypeError(f"Changing attribute type for {name} from "
                                f"{type(existing)} to {type(value)} is not allowed")
        if isinstance(value, Block):
            self._children[name] = value
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block
        return block

    # -------------------------------------------------------- properties ---
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All Parameters of self + descendants, optionally regex-filtered
        (parity: block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    # ------------------------------------------------------------- init ----
    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def zero_grad(self):
        self.collect_params().zero_grad()

    # ---------------------------------------------------------- save/load --
    def save_parameters(self, filename, deduplicate=False):
        """parity: gluon/block.py:418 — params keyed by attribute-path names
        so load is prefix-independent."""
        from ..ndarray import utils as nd_utils

        arg_dict = {name: p.data() for name, p in
                    self._collect_params_with_structure().items()}
        nd_utils.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import utils as nd_utils

        loaded = nd_utils.load(filename)
        params = self._collect_params_with_structure()
        if not allow_missing:
            for name in params:
                assert name in loaded, \
                    f"Parameter {name!r} missing in {filename!r}"
        for name, value in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise ValueError(f"Parameter {name!r} in file is not in Block")
                continue
            params[name].set_data(value)

    def _collect_params_with_structure(self, prefix=""):
        """Structural (attribute-path) parameter names."""
        ret = OrderedDict()
        for name, p in self._reg_params.items():
            ret[prefix + name] = p
        for cname, child in self._children.items():
            ret.update(child._collect_params_with_structure(
                prefix + cname + "."))
        return ret

    # ------------------------------------------------------------ forward --
    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary after one forward (parity:
        block.py summary)."""
        lines = [f"{'Layer':<40}{'Output':<25}{'Params':<12}"]
        total = [0]

        def walk(block, depth=0):
            own = sum(int(p.data().size) for p in block._reg_params.values()
                      if p._data is not None)
            total[0] += own
            lines.append(f"{'  ' * depth + type(block).__name__:<40}"
                         f"{'-':<25}{own:<12}")
            for c in block._children.values():
                walk(c, depth + 1)

        walk(self)
        lines.append(f"Total params: {total[0]}")
        print("\n".join(lines))

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for name, child in self._children.items():
            s += f"  ({name}): {child!r}\n".replace("\n", "\n  ")[2:] + "\n"
        return s + ")"


class HybridBlock(Block):
    """A Block whose forward can be traced into one compiled executable
    (parity: gluon/block.py:970)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_op = None

    def hybridize(self, active=True, **kwargs):
        """parity: block.py:1331 — recursively enable compiled execution.
        static_alloc/static_shape flags are accepted and ignored (XLA always
        memory-plans statically)."""
        self._active = active
        self._flags = kwargs
        self._cached_op = None
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child.hybridize(active, **kwargs)

    def _clear_cached_op(self):
        self._cached_op = None
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child._clear_cached_op()

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from inputs. Layers with
        input-dependent parameter shapes override this (parity: the
        _deferred_infer_shape symbolic pass, block.py:1143)."""
        raise ValueError(
            f"{type(self).__name__} has parameters with unknown shape. "
            "Override infer_shape or provide in_units/in_channels.")

    def _materialize_params(self, *args):
        """Fetch own param values, finishing deferred init if needed."""
        try:
            return {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(*args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            return {name: p.data() for name, p in self._reg_params.items()}

    def __call__(self, *args):
        from ..symbol import Symbol

        if any(isinstance(a, Symbol) for a in args):
            return self.forward(*args)  # symbolic tracing path
        if self._active and current_trace() is None:
            if self._cached_op is not None:  # hot path: no tree walk
                return self._cached_op(*args)
            tree_params = self.collect_params()
            pending = [p for p in tree_params.values() if p._data is None]
            if pending:
                # first call resolves deferred shapes eagerly; compile from
                # the next call (parity: dynamic-mode CachedOp re-planning)
                return self.forward(*args)
            self._build_cache(tree_params)
            return self._cached_op(*args)
        return self.forward(*args)

    def _build_cache(self, tree_params=None):
        """parity: block.py:1067 _build_cache → ndarray.CachedOp."""
        tree_params = tree_params or self.collect_params()
        handles = [p.data() for p in tree_params.values()]
        self._cached_op = CachedOp(self.forward, handles,
                                   flags=self._flags.items())

    def forward(self, x, *args):
        """Default forward: ndarray branch dispatches hybrid_forward with
        this block's params; a Symbol input traces the graph symbolically
        (parity: block.py:1471 two-branch dispatch)."""
        from ..symbol import Symbol

        if isinstance(x, Symbol):
            from .. import symbol as F

            params = {name: p.var() for name, p in self._reg_params.items()}
            return self.hybrid_forward(F, x, *args, **params)
        from .. import ndarray as F

        params = self._materialize_params(x, *args)
        return self.hybrid_forward(F, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _trace_symbol(self, input_names=("data",)):
        """Trace this block into a Symbol graph by running forward with
        Symbol inputs (the reference traces hybrid_forward with Symbol
        proxies, block.py:1067)."""
        from .. import symbol as sym_mod

        inputs = [sym_mod.var(n) for n in input_names]
        out = self.forward(*inputs)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        return out

    def export(self, path, epoch=0, remove_amp_cast=True,
               input_names=("data",)):
        """parity: block.py:1416 — emit `path-symbol.json` +
        `path-%04d.params` loadable by SymbolBlock.imports (and shaped like
        the reference's deployment artifacts). Multi-input blocks pass
        their input names via `input_names`."""
        from ..ndarray import utils as nd_utils

        sym = self._trace_symbol(input_names)
        sym.save(f"{path}-symbol.json")
        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        save_dict = {}
        for name, param in self.collect_params().items():
            if name in arg_names:
                save_dict[f"arg:{name}"] = param.data()
            elif name in aux_names:
                save_dict[f"aux:{name}"] = param.data()
        nd_utils.save(f"{path}-{epoch:04d}.params", save_dict)
        return sym

    def optimize_for(self, x, *args, backend=None, **kwargs):
        self.hybridize()
        return self(x, *args)


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a Gluon block (parity: gluon/block.py:1533).

    Every symbol argument/aux that is not an input becomes a Parameter
    (aux states as grad_req='null'), so the imported graph trains and
    saves like any other Gluon block."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from .. import symbol as sym_mod

        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sb_inputs = [i if isinstance(i, sym_mod.Symbol)
                           else sym_mod.var(str(i)) for i in inputs]
        self._sb_outputs = outputs
        input_names = {s.name for s in self._sb_inputs}
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            if name not in input_names:
                self.params.get(name, grad_req="null",
                                allow_deferred_init=True,
                                differentiable=False)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """parity: block.py SymbolBlock.imports — load an export()ed (or
        reference-produced) symbol.json + params pair."""
        from .. import symbol as sym_mod

        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file:
            block.collect_params().initialize(ctx=ctx)
            block.collect_params().load(param_file, ctx=ctx,
                                        allow_missing=False,
                                        ignore_extra=True)
        return block

    def infer_shape(self, *args):
        """Resolve deferred param shapes from the input shapes via the
        symbol's shape inference (reference: deferred-init symbolic pass)."""
        names = [s.name for s in self._sb_inputs]
        hints = {n: tuple(a.shape) for n, a in zip(names, args)}
        shapes, _ = self._sb_outputs._infer(hints, {})
        for name, p in self.collect_params().items():
            got = shapes.get(("var", name))
            if got is not None and (p.shape is None or
                                    any(s == 0 for s in p.shape)):
                p.shape = got

    def forward(self, *args):
        from .parameter import DeferredInitializationError

        names = [s.name for s in self._sb_inputs]
        feed = dict(zip(names, args))
        params = self.collect_params()
        try:
            param_feed = {name: p.data() for name, p in params.items()}
        except DeferredInitializationError:
            self.infer_shape(*args)
            for p in params.values():
                p._finish_deferred_init()
            param_feed = {name: p.data() for name, p in params.items()}
        feed.update(param_feed)
        aux_handles = {name: p.data() for name, p in params.items()
                       if p._grad_req == "null"}
        return self._sb_outputs.eval_nd(feed, aux_handles)
