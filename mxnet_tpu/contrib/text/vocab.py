"""Indexed vocabulary (parity: `python/mxnet/contrib/text/vocab.py:28`)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Token index built from a `collections.Counter`.

    Indexing order: unknown token at 0, then reserved tokens, then counter
    keys by descending frequency (ties broken alphabetically), truncated
    to `most_freq_count` and filtered by `min_freq` — the reference's
    ordering contract (vocab.py:107), which checkpointed embedding
    matrices depend on.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            reserved = set(reserved_tokens)
            if len(reserved) != len(reserved_tokens):
                raise ValueError("reserved_tokens must not be duplicated")
            if unknown_token in reserved:
                raise ValueError(
                    "unknown_token must not appear in reserved_tokens")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens is not None else None)
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        existing = set(self._idx_to_token)
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        kept = 0
        for token, freq in pairs:
            if freq < min_freq or (most_freq_count is not None
                                   and kept >= most_freq_count):
                break
            if token in existing:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Index/indices -> token(s); out-of-range raises ValueError."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range "
                                 f"[0, {len(self._idx_to_token)})")
            out.append(self._idx_to_token[i])
        return out[0] if single else out
