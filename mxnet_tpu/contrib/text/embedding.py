"""Token embeddings (parity: `python/mxnet/contrib/text/embedding.py`).

Loads pretrained word vectors in the GloVe / fastText text formats into an
`(vocab, vec_len)` NDArray lookup table. The reference downloads archives
from public URLs on demand (embedding.py:200); this environment has no
egress, so `GloVe`/`FastText` resolve their files from the local cache
directory (``$MXNET_HOME/embeddings/<name>/``, default
``~/.mxnet/embeddings``) and raise a clear error telling the user where
to place the file. `CustomEmbedding` loads any whitespace-delimited
vector file directly.
"""
from __future__ import annotations

import io
import os

import numpy as np

from . import vocab as _vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_EMBEDDING_REGISTRY = {}


def register(embedding_cls):
    """Register a `_TokenEmbedding` subclass under its lowercase name
    (parity: embedding.py:40)."""
    name = embedding_cls.__name__.lower()
    _EMBEDDING_REGISTRY[name] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding, e.g.
    ``create('glove', pretrained_file_name='glove.6B.50d.txt')``
    (parity: embedding.py:63)."""
    name = embedding_name.lower()
    if name not in _EMBEDDING_REGISTRY:
        raise KeyError(
            f"unknown embedding {embedding_name!r}; registered: "
            f"{sorted(_EMBEDDING_REGISTRY)}")
    return _EMBEDDING_REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names, per embedding or as a dict
    (parity: embedding.py:90)."""
    if embedding_name is not None:
        return list(
            _EMBEDDING_REGISTRY[embedding_name.lower()]
            .pretrained_file_names)
    return {name: list(cls.pretrained_file_names)
            for name, cls in _EMBEDDING_REGISTRY.items()}


class TokenEmbedding(_vocab.Vocabulary):
    """Base token embedding: a Vocabulary plus an idx->vector NDArray
    table (parity: embedding.py:133 `_TokenEmbedding`).

    Subclasses provide the vector source; this class owns indexing,
    lookup and update. Vectors live in an `mx.nd.NDArray` of shape
    ``(len(self), vec_len)``; row 0 (the unknown token) comes from
    `init_unknown_vec`.
    """

    pretrained_file_names = ()

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = None
        self._idx_to_vec = None

    # ------------------------------------------------------------- loading --
    def _load_embedding(self, pretrained_file_path, elem_delim=" ",
                        init_unknown_vec=None, encoding="utf8"):
        """Parse a text vector file: one token per line, vector elements
        separated by `elem_delim` (parity: embedding.py:232)."""
        from ... import nd

        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise FileNotFoundError(
                f"embedding file not found: {pretrained_file_path}")
        vecs = []
        vec_len = None
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2:
                    continue  # fastText header line: "<count> <dim>"
                token, elems = parts[0], parts[1:]
                if len(elems) <= 1:
                    continue  # malformed line — reference warns and skips
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    continue
                if token == self.unknown_token:
                    # the file's own unknown vector becomes row 0
                    # (parity: embedding.py:262 loaded_unknown_vec)
                    if loaded_unknown_vec is None:
                        loaded_unknown_vec = np.asarray(elems,
                                                        dtype=np.float32)
                    continue
                if token in self._token_to_idx:
                    continue  # first occurrence wins
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(np.asarray(elems, dtype=np.float32))
        if vec_len is None:
            raise ValueError(
                f"no valid vectors found in {pretrained_file_path}")
        self._vec_len = vec_len
        table = np.zeros((len(self), vec_len), dtype=np.float32)
        # file-provided unknown vector wins over the initializer
        # (parity: embedding.py:300)
        if loaded_unknown_vec is not None:
            table[0] = loaded_unknown_vec
        elif init_unknown_vec is not None:
            unk = init_unknown_vec(shape=(vec_len,))
            table[0] = unk.asnumpy() if hasattr(unk, "asnumpy") \
                else np.asarray(unk)
        if vecs:
            table[len(self) - len(vecs):] = np.stack(vecs)
        self._idx_to_vec = nd.array(table)

    def _build_from_vocabulary(self, vocabulary, source_embeddings):
        """Restrict `source_embeddings` to `vocabulary`'s tokens
        (parity: embedding.py:349)."""
        from ... import nd

        parts = [emb.get_vecs_by_tokens(list(vocabulary.idx_to_token))
                 for emb in source_embeddings]
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens
        self._idx_to_vec = nd.concat(*parts, dim=1) if len(parts) > 1 \
            else parts[0]
        self._vec_len = int(self._idx_to_vec.shape[1])

    # -------------------------------------------------------------- lookup --
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get row 0
        (parity: embedding.py:370)."""
        from ... import nd

        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            idxs = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), 0)) for t in toks]
        else:
            idxs = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = nd.take(self._idx_to_vec,
                       nd.array(idxs, dtype="int32"))
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite rows for known tokens (parity: embedding.py:415)."""
        assert self._idx_to_vec is not None, "no embedding loaded"
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if single and len(new_vectors.shape) == 1:
            new_vectors = new_vectors.reshape((1, -1))
        idxs = []
        for t in toks:
            if t not in self._token_to_idx:
                raise ValueError(
                    f"token {t!r} is unknown; only vectors of indexed "
                    "tokens can be updated")
            idxs.append(self._token_to_idx[t])
        # row-wise device-side writes; no whole-table host round-trip
        new_vectors = new_vectors.reshape((len(idxs), -1))
        for row, i in enumerate(idxs):
            self._idx_to_vec[i] = new_vectors[row]

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if cls.pretrained_file_names and \
                pretrained_file_name not in cls.pretrained_file_names:
            raise KeyError(
                f"{pretrained_file_name!r} is not a known "
                f"{cls.__name__} file; choose from "
                f"{sorted(cls.pretrained_file_names)}")

    @classmethod
    def _resolve_local_file(cls, embedding_root, pretrained_file_name):
        """Local-cache stand-in for the reference's archive download
        (embedding.py:200): the vector file must already sit at
        ``<root>/<clsname>/<file>``."""
        embedding_root = os.path.expanduser(embedding_root)
        path = os.path.join(embedding_root, cls.__name__.lower(),
                            pretrained_file_name)
        if not os.path.isfile(path):
            raise FileNotFoundError(
                f"pretrained file {pretrained_file_name!r} not present at "
                f"{path}; this environment has no network egress — place "
                "the extracted vector file there (the reference would "
                "download it from apache-mxnet.s3)")
        return path


# keep the reference's private alias importable (embedding.py:133)
_TokenEmbedding = TokenEmbedding


def _default_embedding_root():
    return os.path.join(
        os.environ.get("MXNET_HOME", os.path.join("~", ".mxnet")),
        "embeddings")


@register
class GloVe(TokenEmbedding):
    """GloVe vectors from a local file (parity: embedding.py:481)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=None, init_unknown_vec=None,
                 vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._resolve_local_file(
            embedding_root or _default_embedding_root(),
            pretrained_file_name)
        self._load_embedding(path, " ",
                             init_unknown_vec=init_unknown_vec)
        if vocabulary is not None:
            self._build_from_vocabulary(vocabulary, [self])


@register
class FastText(TokenEmbedding):
    """fastText vectors from a local file (parity: embedding.py:553)."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.fr.vec",
        "wiki.de.vec", "wiki.es.vec", "wiki.ru.vec", "wiki.ja.vec",
        "crawl-300d-2M.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, init_unknown_vec=None,
                 vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._resolve_local_file(
            embedding_root or _default_embedding_root(),
            pretrained_file_name)
        self._load_embedding(path, " ",
                             init_unknown_vec=init_unknown_vec)
        if vocabulary is not None:
            self._build_from_vocabulary(vocabulary, [self])


@register
class CustomEmbedding(TokenEmbedding):
    """Vectors from any local text file: ``token<delim>e1<delim>e2...``
    per line (parity: embedding.py:635)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=None, vocabulary=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec=init_unknown_vec,
                             encoding=encoding)
        if vocabulary is not None:
            self._build_from_vocabulary(vocabulary, [self])


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (parity: embedding.py:677)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__()
        self._build_from_vocabulary(vocabulary, token_embeddings)
