"""Text utilities (parity: `python/mxnet/contrib/text/__init__.py`).

Vocabulary indexing, token-embedding loading (GloVe / fastText file
formats, custom files, composites) and tokenization helpers. Embedding
*matrices* come back as NDArrays ready to drop into
`gluon.nn.Embedding(...).weight` — the TPU path is simply a device-side
gather through that layer.
"""
from __future__ import annotations

from . import embedding, utils, vocab
from .vocab import Vocabulary

__all__ = ["embedding", "utils", "vocab", "Vocabulary"]
