"""Contrib namespace (parity: `python/mxnet/contrib/__init__.py`).

Hosts the experimental subsystems the reference ships under
`mxnet.contrib`: `amp` (mixed precision — the real implementation lives
at `mxnet_tpu.amp` and is aliased here at its reference import path) and
`quantization`. Contrib *operators* (`mx.nd.contrib.*` /
`mx.sym.contrib.*`) are regular registry ops with the `_contrib_` prefix.
"""
from __future__ import annotations

from .. import amp  # reference import path: mx.contrib.amp

__all__ = ["amp", "quantization", "svrg_optimization", "text"]


def __getattr__(name):
    if name in ("quantization", "svrg_optimization", "text"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
