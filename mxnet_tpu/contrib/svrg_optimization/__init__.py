"""SVRG optimization (parity: `python/mxnet/contrib/svrg_optimization/`).

Stochastic Variance Reduced Gradient (Johnson & Zhang, NIPS'13) as a
Module-API wrapper: periodically snapshot the weights w~ and the full
dataset gradient mu = (1/N) sum_i grad f_i(w~); each minibatch step then
descends along  g_i(w) - g_i(w~) + mu,  an unbiased, variance-reduced
gradient estimate.

TPU-first redesign: the reference routes full-gradient accumulation
through a kvstore with a private `_SVRGOptimizer`/`_AssignmentOptimizer`
pair (svrg_optimizer.py:25,50). Here the corrected gradient is computed
directly with fused NDArray arithmetic on device and handed to the
ordinary updater — no optimizer impersonation, and the aux (snapshot)
module reuses the main module's compiled executor cache.
"""
from __future__ import annotations

from .svrg_module import SVRGModule

__all__ = ["SVRGModule"]
