"""SVRGModule: Module-API SVRG training.

parity: `python/mxnet/contrib/svrg_optimization/svrg_module.py:30` — same
public surface (`update_full_grads`, `update_svrg_gradients`, `update`,
`fit` with `update_freq`), different machinery: instead of smuggling the
full-gradient accumulation through a kvstore with a fake optimizer
(svrg_optimizer.py:25), the snapshot module's per-batch gradient and the
stored full gradient are combined with device-side NDArray arithmetic and
the result is handed to the ordinary fused updater.
"""
from __future__ import annotations

import logging
import time

from ...module.module import Module


class SVRGModule(Module):
    """SVRG-optimizing Module (parity: svrg_module.py:30).

    Every `update_freq` epochs, `update_full_grads(train_data)` snapshots
    the weights (w~) and accumulates the exact full-dataset gradient mu.
    Each subsequent minibatch update descends along

        g_i(w) - g_i(w~) + mu

    computed by running the batch through BOTH the live module and an
    internal auxiliary module holding the snapshot weights.

    Parameters match `Module`, plus:

    update_freq : int
        Full-gradient refresh period, in epochs.
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, update_freq=None):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, work_load_list=work_load_list,
                         fixed_param_names=fixed_param_names,
                         state_names=state_names)
        if not isinstance(update_freq, int) or update_freq < 1:
            raise TypeError("update_freq must be a positive integer, got "
                            f"{update_freq!r}")
        self.update_freq = update_freq
        # snapshot module: same symbol/ctx, params = w~ (svrg_module.py:90)
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context,
                               fixed_param_names=fixed_param_names)
        self._full_grads = None  # name -> NDArray mu accumulated over data

    # ---------------------------------------------------------------- bind --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def reshape(self, data_shapes, label_shapes=None):
        # simple_bind zero-fills fresh executors; carry the live weights
        # across the rebind (parity: Module.reshape preserves contents)
        saved = self.get_params() if self.params_initialized else None
        super().bind(data_shapes, label_shapes, self.for_training,
                     self._inputs_need_grad, force_rebind=True)
        self._mod_aux.bind(data_shapes, label_shapes, self.for_training,
                           self._inputs_need_grad, force_rebind=True)
        if saved is not None:
            arg_p, aux_p = saved
            super().init_params(arg_params=arg_p, aux_params=aux_p,
                                allow_missing=False, force_init=True)
            self._mod_aux.init_params(arg_params=arg_p, aux_params=aux_p,
                                      allow_missing=False, force_init=True)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        super().init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=allow_missing,
                            force_init=force_init, allow_extra=allow_extra)
        # aux starts at the same point; real snapshot happens in
        # update_full_grads
        arg_p, aux_p = self.get_params()
        self._mod_aux.init_params(arg_params=arg_p, aux_params=aux_p,
                                  allow_missing=False, force_init=True)

    # -------------------------------------------------------- SVRG pieces --
    def update_full_grads(self, train_data):
        """Snapshot w~ := w and compute mu = mean over all batches of
        grad f(w~) (parity: svrg_module.py:292)."""
        assert self.binded and self.params_initialized
        arg_p, aux_p = self.get_params()
        self._mod_aux.init_params(arg_params=arg_p, aux_params=aux_p,
                                  allow_missing=False, force_init=True)
        accum = {}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                if name in accum:
                    accum[name] += g
                else:
                    accum[name] = g.copy()
            nbatch += 1
        assert nbatch > 0, "update_full_grads needs a non-empty iterator"
        for name in accum:
            accum[name] /= nbatch
        self._full_grads = accum
        train_data.reset()

    def update_svrg_gradients(self):
        """Rewrite the live gradients in place to the variance-reduced form
        g(w) - g(w~) + mu (parity: svrg_module.py:382,360)."""
        assert self._full_grads is not None, \
            "call update_full_grads before the epoch's first update"
        for name in self._param_names:
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            g_tilde = self._mod_aux._exec.grad_dict.get(name)
            mu = self._full_grads.get(name)
            if g_tilde is None or mu is None:
                continue
            g[:] = g - g_tilde + mu

    def forward(self, data_batch, is_train=None):
        super().forward(data_batch, is_train)
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._mod_aux.forward(data_batch, is_train=True)

    def backward(self, out_grads=None):
        super().backward(out_grads)
        if self._mod_aux.binded:
            self._mod_aux.backward(out_grads)

    def update(self):
        """Apply the optimizer along the SVRG-corrected direction
        (parity: svrg_module.py:274)."""
        self.update_svrg_gradients()
        super().update()

    # ------------------------------------------------------------- fit -----
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """Module.fit with a full-gradient refresh every `update_freq`
        epochs (parity: svrg_module.py:395)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ... import initializer as init_mod
        from ... import metric as metric_mod
        from ...module.base_module import BatchEndParam, _as_list

        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params,
                            force_init=force_init)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        if monitor is not None:
            self.install_monitor(monitor)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            # resume-safe: a begin_epoch off the refresh grid still needs an
            # initial mu before the first update
            if self._full_grads is None or epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            for cb in _as_list(epoch_end_callback):
                arg_p, aux_p = self.get_params()
                cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
