"""Post-training int8 quantization (parity:
`python/mxnet/contrib/quantization.py` over
`src/operator/quantization/quantize_graph_pass.cc` + `calibrate.cc`).

Pipeline (same three phases as the reference):
  1. **Calibrate** — run `calib_data` through the fp32 graph collecting
     per-quantized-op input ranges ('naive' min/max, or 'entropy' via a
     percentile clip — the reference's KL-divergence search is approximated
     by a 99.99th-percentile clip, which it converges to for the common
     activation distributions).
  2. **Pass** — rebuild the symbol DAG replacing Convolution /
     FullyConnected nodes with `_contrib_quantized_conv` /
     `_contrib_quantized_fully_connected` nodes wired to int8 weight +
     per-channel scale variables and carrying the calibrated activation
     range as attrs.
  3. **Params** — quantize the weights per-output-channel symmetric int8;
     biases stay fp32 (added after dequantize, like the reference).

On the MXU int8 matmul runs at 2x the bf16 rate, so this is a genuine
speed path, not emulation.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["quantize_model", "quantize_net", "quantize_graph"]

_QUANTIZABLE = {"Convolution": "_contrib_quantized_conv",
                "FullyConnected": "_contrib_quantized_fully_connected"}


def _collect_ranges(sym, arg_params, aux_params, calib_data, data_names,
                    num_calib_examples, calib_mode, ctx):
    """Phase 1: per-node input activation ranges {node_name: (min, max)}."""
    from ..symbol.symbol import _topo

    # the inputs we must observe: the data feeding each quantizable node
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    watch = {}  # output_name -> [node names consuming it as data]
    for node in _topo(sym._entries):
        if node.op in _QUANTIZABLE:
            src, oi = node.inputs[0]
            if src.is_var:
                oname = src.name
            elif src.num_outputs == 1:
                oname = f"{src.name}_output"
            else:
                oname = f"{src.name}_output{oi}"
            watch.setdefault(oname, []).append(node.name)
    ranges = {}
    seen = 0
    for batch in calib_data:
        feed = dict(zip(data_names, batch.data))
        feed.update(arg_params)
        feed.update(aux_params)
        outs = internals.eval_with(feed)
        for oname, arr in zip(out_names, outs):
            if oname not in watch:
                continue
            a = arr.asnumpy().astype(_np.float64)
            if calib_mode == "entropy":
                lo = float(_np.percentile(a, 0.01))
                hi = float(_np.percentile(a, 99.99))
            else:  # naive
                lo, hi = float(a.min()), float(a.max())
            for consumer in watch[oname]:
                if consumer in ranges:
                    plo, phi = ranges[consumer]
                    ranges[consumer] = (min(plo, lo), max(phi, hi))
                else:
                    ranges[consumer] = (lo, hi)
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    calib_data.reset()
    return ranges


def quantize_graph(sym, excluded_sym_names=(), ranges=None):
    """Phase 2: DAG surgery. Returns (qsym, [weight var names quantized])."""
    from ..symbol.symbol import Symbol, _Node, _topo

    ranges = ranges or {}
    excluded = set(excluded_sym_names or ())
    mapping = {}  # id(old node) -> new node
    quantized_weights = []
    for node in _topo(sym._entries):
        new_inputs = [(mapping[id(c)], oi) for c, oi in node.inputs]
        if node.op in _QUANTIZABLE and node.name not in excluded \
                and node.name in ranges and len(node.inputs) >= 2 \
                and node.inputs[1][0].is_var:
            lo, hi = ranges[node.name]
            qop = _QUANTIZABLE[node.op]
            attrs = dict(node.attrs)
            attrs["min_calib_range"] = lo
            attrs["max_calib_range"] = hi
            # inputs: data, weight->int8 var, scale var, [bias];
            # new vars keyed off the ORIGINAL weight var name so params
            # line up whatever the node was called (gluon export names
            # nodes and params differently)
            wname = node.inputs[1][0].name
            data_in = new_inputs[0]
            qw = _Node(None, wname + "_quantize", {}, [])
            sc = _Node(None, wname + "_scale", {}, [])
            ins = [data_in, (qw, 0), (sc, 0)]
            if len(new_inputs) > 2:  # bias present
                ins.append(new_inputs[2])
            new = _Node(qop, node.name, attrs, ins,
                        num_outputs=node.num_outputs)
            quantized_weights.append(wname)
        else:
            new = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                        num_outputs=node.num_outputs)
        mapping[id(node)] = new
    entries = [(mapping[id(n)], i) for n, i in sym._entries]
    return Symbol(entries), quantized_weights


def _quantize_params(arg_params, quantized_weight_names):
    """Phase 3: per-output-channel symmetric int8 weights + fp32 scales."""
    from ..ndarray import array

    qargs = {}
    for name, arr in arg_params.items():
        if name in quantized_weight_names:
            w = arr.asnumpy()
            flat = w.reshape(w.shape[0], -1)
            absmax = _np.abs(flat).max(axis=1)
            scale = _np.where(absmax > 0, absmax / 127.0, 1.0) \
                .astype(_np.float32)
            q = _np.clip(_np.round(flat / scale[:, None]), -127, 127) \
                .astype(_np.int8).reshape(w.shape)
            qargs[name + "_quantize"] = array(q, dtype="int8")
            qargs[name + "_scale"] = array(scale)
        else:
            qargs[name] = arr
    return qargs


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None):
    """parity: contrib/quantization.py quantize_model.

    Returns (qsym, qarg_params, aux_params) ready for Module/bind.
    """
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError("only int8 symmetric quantization is supported")
    if calib_data is None or calib_mode == "none":
        raise ValueError("calib_data is required (the TPU pass bakes "
                         "activation ranges into the executable)")
    ranges = _collect_ranges(sym, arg_params, aux_params, calib_data,
                             list(data_names), num_calib_examples,
                             calib_mode, ctx)
    qsym, qnames = quantize_graph(sym, excluded_sym_names or (), ranges)
    qargs = _quantize_params(arg_params, set(qnames))
    return qsym, qargs, dict(aux_params)


def quantize_net(network, calib_data, data_shape=None, calib_mode="naive",
                 num_calib_examples=None, excluded_layers=None, ctx=None,
                 logger=None):
    """Quantize a (Hybrid)Block: export -> quantize_model -> SymbolBlock
    (parity: contrib/quantization.py quantize_net)."""
    import mxnet_tpu as mx
    from ..gluon import SymbolBlock

    if not isinstance(calib_data, mx.io.DataIter):
        calib_data = mx.io.NDArrayIter(calib_data, batch_size=min(
            32, calib_data.shape[0]), label_name=None)
    first = calib_data.provide_data[0]
    x = mx.nd.zeros(first.shape)
    network(x)  # materialize params
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        prefix = d + "/net"
        network.export(prefix, 0)
        sym, args, auxs = mx.model.load_checkpoint(prefix, 0)
        qsym, qargs, auxs = quantize_model(
            sym, args, auxs, data_names=(first.name,),
            calib_data=calib_data, calib_mode=calib_mode,
            num_calib_examples=num_calib_examples,
            excluded_sym_names=excluded_layers)
        # round-trip through the tested export format
        mx.model.save_checkpoint(prefix + "-q", 0, qsym, qargs, auxs)
        block = SymbolBlock.imports(prefix + "-q-symbol.json",
                                    [first.name],
                                    prefix + "-q-0000.params", ctx=ctx)
    return block
