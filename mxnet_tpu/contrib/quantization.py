"""Post-training int8 quantization (parity:
`python/mxnet/contrib/quantization.py` over
`src/operator/quantization/quantize_graph_pass.cc` + `calibrate.cc`).

Pipeline (same three phases as the reference):
  1. **Calibrate** — run `calib_data` through the fp32 graph collecting
     per-quantized-op input ranges. Three modes:

     * ``"naive"``      — running min/max of every observed batch,
     * ``"entropy"``    — the reference's KL-divergence threshold search
       (`calibrate.cc` ``GetOptimalThreshold``): a 2048-bin histogram is
       accumulated per collected tensor (host-side numpy, constant bin
       width, range grown exactly like the reference's
       ``combine_histogram``), then every candidate threshold is swept
       and the one minimizing KL(P ‖ Q) between the clipped reference
       distribution P and its int8-requantized projection Q wins,
     * ``"percentile"`` — the pre-entropy approximation kept for A/B: a
       99.99th-percentile clip (what ``"entropy"`` used to mean here
       before the true KL search landed).

  2. **Pass** — rebuild the symbol DAG replacing Convolution /
     FullyConnected nodes with `_contrib_quantized_conv` /
     `_contrib_quantized_fully_connected` nodes wired to int8 weight +
     scale variables and carrying the calibrated activation range as
     attrs (the dequantize is folded into the op's output scale, so the
     compiled XLA graph stays int8-GEMM-shaped); Embedding nodes become
     `_contrib_quantized_embedding` (int8 table gather) + dequantize —
     the weight-storage win for bandwidth-bound embedding models.

  3. **Params** — quantize the weights symmetric int8, per **output
     channel** by default (``quantize_granularity="channel-wise"``: one
     fp32 scale per output channel) or per tensor
     (``"tensor-wise"``: one scalar scale) for A/B; embedding tables are
     per-tensor. Biases stay fp32 (added after dequantize, like the
     reference).

On the MXU int8 matmul runs at 2x the bf16 rate, so this is a genuine
speed path, not emulation; on CPU (no XLA int8 GEMM kernels) the win
comes from int8 weight *storage* on gather-bound models — see
docs/PERFORMANCE.md "Int8 inference".
"""
from __future__ import annotations

import numpy as _np

__all__ = ["quantize_model", "quantize_net", "quantize_graph",
           "kl_optimal_threshold", "last_calibration", "last_quantization",
           "DEFAULT_NUM_BINS", "DEFAULT_NUM_QUANTIZED_BINS"]

_QUANTIZABLE = {"Convolution": "_contrib_quantized_conv",
                "FullyConnected": "_contrib_quantized_fully_connected"}

#: calibrate.cc uses 8001 bins; 2048 keeps the sweep cheap on host numpy
#: while leaving the int8 projection (255 levels) 8x oversampled.
DEFAULT_NUM_BINS = 2048
#: int8 symmetric: 255 representable levels (-127..127).
DEFAULT_NUM_QUANTIZED_BINS = 255

# introspection for tools/diagnose.py ("Quantization" report): the last
# calibration and the last graph-pass census run in this process
_LAST_CALIB = None
_LAST_PASS = None


def last_calibration():
    """The most recent calibration run in this process (mode, bins,
    per-tensor thresholds/ranges, examples seen) or None."""
    return _LAST_CALIB


def last_quantization():
    """The most recent :func:`quantize_graph` census in this process
    (per-weight granularity kinds, op counts) or None."""
    return _LAST_PASS


# ------------------------------------------------------------ KL search ---

def _smooth(p, eps=0.0001):
    """parity: calibrate.cc SmoothDistribution — add eps mass to the zero
    bins, subtract the compensating mass from nonzero bins so KL(P||Q)
    stays finite; None when infeasible (all-zero or eps overload)."""
    p = p.astype(_np.float64)
    is_zeros = p == 0
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if not n_nonzeros:
        return None
    eps1 = eps * float(n_zeros) / float(n_nonzeros)
    if eps1 >= 1.0:
        return None
    out = p.copy()
    out[is_zeros] = eps
    out[~is_zeros] -= eps1
    return out


def _kl_divergence(p, q):
    """KL(P||Q) over already-positive distributions (normalized here)."""
    p = p / p.sum()
    q = q / q.sum()
    mask = p > 0
    return float(_np.sum(p[mask] * _np.log(p[mask] / q[mask])))


def kl_optimal_threshold(hist, hist_edges,
                         num_quantized_bins=DEFAULT_NUM_QUANTIZED_BINS):
    """The calibrate.cc KL-divergence threshold search, host-side numpy.

    ``hist`` is a histogram over the SYMMETRIC range
    ``(-th, th)`` (even bin count; ``hist_edges`` has ``len(hist)+1``
    entries). The two halves are folded into a histogram of ``|x|``;
    every candidate threshold (each folded bin edge from
    ``num_quantized_bins//2 + 1`` outward) clips the reference
    distribution P at the candidate, dumps the outlier mass into the
    edge bin, projects P onto ``(num_quantized_bins+1)//2`` int8-side
    levels, expands the projection Q back, smooths both, and scores
    KL(P ‖ Q). Returns ``(threshold, kl_divergence)`` for the argmin —
    deterministic: pure numpy, ties broken toward the smaller
    threshold.
    """
    hist = _np.asarray(hist, _np.float64)
    hist_edges = _np.asarray(hist_edges, _np.float64)
    n = hist.size
    if n % 2 or hist_edges.size != n + 1:
        raise ValueError(
            f"kl_optimal_threshold wants an even-bin symmetric histogram; "
            f"got {n} bins / {hist_edges.size} edges")
    mid = n // 2
    # fold onto |x|: bin j covers [j*w, (j+1)*w)
    abs_hist = hist[mid:] + hist[:mid][::-1]
    abs_edges = hist_edges[mid:]
    nq = (num_quantized_bins + 1) // 2  # int8 symmetric: 128 magnitude bins
    if abs_hist.size <= nq:
        # fewer bins than quantized levels: clipping can only lose mass
        return float(abs_edges[-1]), 0.0
    best_th, best_kl = float(abs_edges[-1]), _np.inf
    total = abs_hist.sum()
    if total <= 0:
        return float(abs_edges[-1]), 0.0
    for i in range(nq, abs_hist.size + 1):
        p = abs_hist[:i].copy()
        p[-1] += abs_hist[i:].sum()  # outliers clip into the edge bin
        threshold = float(abs_edges[i])
        # project the i reference bins onto nq quantized levels
        num_merged = i // nq
        q = _np.zeros(i, _np.float64)
        ref = abs_hist[:i]
        nonzero = (ref != 0).astype(_np.float64)
        for j in range(nq):
            start = j * num_merged
            stop = i if j == nq - 1 else start + num_merged
            norm = nonzero[start:stop].sum()
            if norm:
                q[start:stop] = ref[start:stop].sum() / norm
        q[ref == 0] = 0.0
        ps = _smooth(p)
        qs = _smooth(q)
        if ps is None or qs is None:
            continue
        kl = _kl_divergence(ps, qs)
        if kl < best_kl:
            best_kl, best_th = kl, threshold
    return best_th, (0.0 if best_kl is _np.inf else best_kl)


class _HistogramCollector:
    """Per-tensor symmetric histogram accumulated across calib batches
    (parity: the reference collector's ``combine_histogram`` — constant
    bin width, range grown outward when a batch exceeds it)."""

    def __init__(self, num_bins=DEFAULT_NUM_BINS):
        self.num_bins = int(num_bins)
        self.state = {}  # name -> (hist, hist_edges, min, max, th)

    def collect(self, name, arr):
        a = arr.reshape(-1)
        new_min = float(a.min()) if a.size else 0.0
        new_max = float(a.max()) if a.size else 0.0
        new_th = max(abs(new_min), abs(new_max), 1e-8)
        st = self.state.get(name)
        if st is None:
            hist, edges = _np.histogram(a, bins=self.num_bins,
                                        range=(-new_th, new_th))
            self.state[name] = (hist.astype(_np.int64), edges,
                                new_min, new_max, new_th)
            return
        hist, edges, old_min, old_max, old_th = st
        if new_th <= old_th:
            add, _ = _np.histogram(a, bins=hist.size, range=(-old_th, old_th))
            self.state[name] = (hist + add, edges,
                                min(old_min, new_min), max(old_max, new_max),
                                old_th)
            return
        # grow outward keeping the bin width: the old histogram drops
        # unchanged into the middle of the widened one
        old_step = 2.0 * old_th / hist.size
        half_inc = int((new_th - old_th) // old_step + 1)
        # keep the bin count even so the KL fold stays exact
        grown_bins = hist.size + 2 * half_inc
        grown_th = half_inc * old_step + old_th
        add, new_edges = _np.histogram(a, bins=grown_bins,
                                       range=(-grown_th, grown_th))
        add = add.astype(_np.int64)
        add[half_inc:grown_bins - half_inc] += hist
        self.state[name] = (add, new_edges,
                            min(old_min, new_min), max(old_max, new_max),
                            grown_th)

    def thresholds(self, num_quantized_bins=DEFAULT_NUM_QUANTIZED_BINS):
        """{name: (threshold, kl, min_seen, max_seen, bins)} per tensor."""
        out = {}
        for name, (hist, edges, mn, mx, _th) in self.state.items():
            th, kl = kl_optimal_threshold(
                hist, edges, num_quantized_bins=num_quantized_bins)
            out[name] = (th, kl, mn, mx, hist.size)
        return out


# ----------------------------------------------------------- calibration ---

def _collect_ranges(sym, arg_params, aux_params, calib_data, data_names,
                    num_calib_examples, calib_mode, ctx,
                    num_bins=DEFAULT_NUM_BINS, label_names=()):
    """Phase 1: activation ranges.

    Returns ``(ranges, out_ranges)`` — ``ranges`` maps each quantizable
    node name to the calibrated ``(min, max)`` of its data INPUT (mode-
    dependent); ``out_ranges`` maps it to the observed min/max of its own
    OUTPUT (always naive — used for the ONNX ``y_scale`` and requantize
    fusion, where range precision matters less than for activations).
    """
    global _LAST_CALIB
    from ..symbol.symbol import _topo

    # the inputs we must observe: the data feeding each quantizable node,
    # plus each quantizable node's own output
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    watch = {}      # output_name -> [node names consuming it as data]
    out_watch = {}  # output_name -> producing quantizable node name
    for node in _topo(sym._entries):
        if node.op in _QUANTIZABLE:
            src, oi = node.inputs[0]
            if src.is_var:
                oname = src.name
            elif src.num_outputs == 1:
                oname = f"{src.name}_output"
            else:
                oname = f"{src.name}_output{oi}"
            watch.setdefault(oname, []).append(node.name)
            self_out = f"{node.name}_output" if node.num_outputs == 1 \
                else f"{node.name}_output0"
            out_watch[self_out] = node.name
    ranges = {}
    out_ranges = {}
    hists = _HistogramCollector(num_bins) if calib_mode == "entropy" else None
    seen = 0
    batches = 0
    calib_data.reset()  # a freshly-fit iter arrives exhausted
    for batch in calib_data:
        feed = dict(zip(data_names, batch.data))
        # training-style graphs (SoftmaxOutput & co.) carry label vars;
        # feed them through so calibration can eval the full graph
        if label_names and getattr(batch, "label", None):
            feed.update(zip(label_names, batch.label))
        feed.update(arg_params)
        feed.update(aux_params)
        outs = internals.eval_with(feed)
        for oname, arr in zip(out_names, outs):
            watched = oname in watch
            if not watched and oname not in out_watch:
                continue
            # calibration is host-side by design (the reference collects
            # on host too); this is a cold path, not a training loop
            a = arr.asnumpy().astype(_np.float64)  # noqa: host-sync
            if watched:
                if calib_mode == "entropy":
                    hists.collect(oname, a)
                elif calib_mode == "percentile":
                    lo = float(_np.percentile(a, 0.01))
                    hi = float(_np.percentile(a, 99.99))
                else:  # naive
                    lo, hi = float(a.min()), float(a.max())
                if calib_mode != "entropy":
                    for consumer in watch[oname]:
                        if consumer in ranges:
                            plo, phi = ranges[consumer]
                            ranges[consumer] = (min(plo, lo), max(phi, hi))
                        else:
                            ranges[consumer] = (lo, hi)
            if oname in out_watch:
                node = out_watch[oname]
                lo, hi = float(a.min()), float(a.max())
                if node in out_ranges:
                    plo, phi = out_ranges[node]
                    out_ranges[node] = (min(plo, lo), max(phi, hi))
                else:
                    out_ranges[node] = (lo, hi)
        seen += batch.data[0].shape[0]
        batches += 1
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    calib_data.reset()
    if watch and not seen:
        raise ValueError(
            "calibration saw no examples (empty calib_data); the "
            "quantize pass would silently skip every node")
    tensors = {}
    if calib_mode == "entropy":
        ths = hists.thresholds()
        for oname, (th, kl, mn, mx, bins) in ths.items():
            for consumer in watch[oname]:
                ranges[consumer] = (-th, th)
            tensors[oname] = {"threshold": round(th, 6),
                              "kl_divergence": round(kl, 6),
                              "min_seen": round(mn, 6),
                              "max_seen": round(mx, 6), "bins": bins}
    else:
        for oname, consumers in watch.items():
            for c in consumers:
                if c in ranges:
                    lo, hi = ranges[c]
                    tensors[oname] = {"min": round(lo, 6),
                                      "max": round(hi, 6)}
    _LAST_CALIB = {"mode": calib_mode, "num_bins": num_bins,
                   "examples": seen, "batches": batches,
                   "tensors": tensors}
    return ranges, out_ranges


# ------------------------------------------------------------- graph pass ---

def quantize_graph(sym, excluded_sym_names=(), ranges=None, out_ranges=None,
                   quantize_granularity="channel-wise"):
    """Phase 2: DAG surgery. Returns ``(qsym, qspecs)`` where ``qspecs``
    maps each quantized weight var name to its granularity kind
    (``"channel"`` / ``"tensor"`` for conv/dense, ``"embedding"`` for
    int8 embedding tables). Iterating ``qspecs`` yields the weight names
    (the pre-granularity return shape)."""
    global _LAST_PASS
    from ..symbol.symbol import Symbol, _Node, _topo

    if quantize_granularity not in ("channel-wise", "tensor-wise"):
        raise ValueError("quantize_granularity must be 'channel-wise' or "
                         f"'tensor-wise', got {quantize_granularity!r}")
    ranges = ranges or {}
    out_ranges = out_ranges or {}
    excluded = set(excluded_sym_names or ())
    mapping = {}  # id(old node) -> new node
    qspecs = {}
    op_census = {}
    kind = "channel" if quantize_granularity == "channel-wise" else "tensor"
    for node in _topo(sym._entries):
        new_inputs = [(mapping[id(c)], oi) for c, oi in node.inputs]
        if node.op in _QUANTIZABLE and node.name not in excluded \
                and node.name in ranges and len(node.inputs) >= 2 \
                and node.inputs[1][0].is_var:
            lo, hi = ranges[node.name]
            qop = _QUANTIZABLE[node.op]
            attrs = dict(node.attrs)
            attrs["min_calib_range"] = lo
            attrs["max_calib_range"] = hi
            if node.name in out_ranges:
                # observed output range: the ONNX exporter's y_scale and
                # a future requantize fusion both need it
                attrs["min_out_calib_range"] = out_ranges[node.name][0]
                attrs["max_out_calib_range"] = out_ranges[node.name][1]
            # inputs: data, weight->int8 var, scale var, [bias];
            # new vars keyed off the ORIGINAL weight var name so params
            # line up whatever the node was called (gluon export names
            # nodes and params differently)
            wname = node.inputs[1][0].name
            data_in = new_inputs[0]
            qw = _Node(None, wname + "_quantize", {}, [])
            sc = _Node(None, wname + "_scale", {}, [])
            ins = [data_in, (qw, 0), (sc, 0)]
            if len(new_inputs) > 2:  # bias present
                ins.append(new_inputs[2])
            new = _Node(qop, node.name, attrs, ins,
                        num_outputs=node.num_outputs)
            qspecs[wname] = kind
            op_census[qop] = op_census.get(qop, 0) + 1
        elif node.op == "Embedding" and node.name not in excluded \
                and len(node.inputs) >= 2 and node.inputs[1][0].is_var:
            # weight-only int8: gather stays in int8 (4x less table
            # traffic), the dequantize (cast * scale) fuses into the
            # gather's consumer; ids need no activation calibration
            wname = node.inputs[1][0].name
            attrs = {k: v for k, v in node.attrs.items()
                     if k in ("input_dim", "output_dim")}
            qw = _Node(None, wname + "_quantize", {}, [])
            mn = _Node(None, wname + "_min", {}, [])
            mxv = _Node(None, wname + "_max", {}, [])
            qe = _Node("_contrib_quantized_embedding", node.name, attrs,
                       [new_inputs[0], (qw, 0), (mn, 0), (mxv, 0)],
                       num_outputs=3)
            new = _Node("_contrib_dequantize", node.name + "_dequantize",
                        {}, [(qe, 0), (qe, 1), (qe, 2)])
            qspecs[wname] = "embedding"
            op_census["_contrib_quantized_embedding"] = \
                op_census.get("_contrib_quantized_embedding", 0) + 1
        else:
            new = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                        num_outputs=node.num_outputs)
        mapping[id(node)] = new
    entries = [(mapping[id(n)], i) for n, i in sym._entries]
    _LAST_PASS = {
        "granularity": quantize_granularity,
        "weights": dict(qspecs),
        "per_channel": sum(1 for k in qspecs.values() if k == "channel"),
        "per_tensor": sum(1 for k in qspecs.values()
                          if k in ("tensor", "embedding")),
        "ops": op_census,
    }
    return Symbol(entries), qspecs


# ---------------------------------------------------------------- params ---

def _quantize_params(arg_params, qspecs):
    """Phase 3: symmetric int8 weights + fp32 scales.

    Granularity rides in ``qspecs`` (from :func:`quantize_graph`):
    ``"channel"`` → one scale per output channel (axis 0),
    ``"tensor"`` → one scalar scale, ``"embedding"`` → per-tensor int8
    table published as ``_min``/``_max`` range params (the reference's
    quantized-embedding contract)."""
    from ..ndarray import array

    if not isinstance(qspecs, dict):  # bare name iterable: channel-wise
        qspecs = {n: "channel" for n in qspecs}
    qargs = {}
    for name, arr in arg_params.items():
        kind = qspecs.get(name)
        if kind is None:
            qargs[name] = arr
            continue
        # cold path by design: weights quantize once at model-prep time
        w = arr.asnumpy()  # noqa: host-sync
        if kind == "embedding":
            absmax = float(_np.abs(w).max())
            absmax = absmax if absmax > 0 else 1.0
            scale = absmax / 127.0
            q = _np.clip(_np.round(w / scale), -127, 127).astype(_np.int8)
            qargs[name + "_quantize"] = array(q, dtype="int8")
            qargs[name + "_min"] = array(
                _np.asarray([-absmax], _np.float32))
            qargs[name + "_max"] = array(
                _np.asarray([absmax], _np.float32))
            continue
        flat = w.reshape(w.shape[0], -1)
        if kind == "tensor":
            absmax = _np.asarray([_np.abs(flat).max()])
        else:  # channel
            absmax = _np.abs(flat).max(axis=1)
        scale = _np.where(absmax > 0, absmax / 127.0, 1.0) \
            .astype(_np.float32)
        q = _np.clip(_np.round(flat / scale[:, None] if kind == "channel"
                               else flat / scale), -127, 127) \
            .astype(_np.int8).reshape(w.shape)
        qargs[name + "_quantize"] = array(q, dtype="int8")
        qargs[name + "_scale"] = array(scale)
    return qargs


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None,
                   quantize_granularity="channel-wise",
                   calib_bins=DEFAULT_NUM_BINS):
    """parity: contrib/quantization.py quantize_model.

    ``calib_mode``: ``"naive"`` (min/max), ``"entropy"`` (the real
    calibrate.cc KL threshold search) or ``"percentile"`` (the legacy
    99.99% clip, kept for A/B). ``quantize_granularity``:
    ``"channel-wise"`` (default, one scale per output channel) or
    ``"tensor-wise"``. Returns (qsym, qarg_params, aux_params) ready for
    Module/bind.
    """
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError("only int8 symmetric quantization is supported")
    if calib_mode not in ("naive", "entropy", "percentile"):
        raise ValueError(
            f"calib_mode must be naive|entropy|percentile, got "
            f"{calib_mode!r}")
    if calib_data is None or calib_mode == "none":
        raise ValueError("calib_data is required (the TPU pass bakes "
                         "activation ranges into the executable)")
    ranges, out_ranges = _collect_ranges(
        sym, arg_params, aux_params, calib_data, list(data_names),
        num_calib_examples, calib_mode, ctx, num_bins=calib_bins,
        label_names=list(label_names or ()))
    qsym, qspecs = quantize_graph(
        sym, excluded_sym_names or (), ranges, out_ranges,
        quantize_granularity=quantize_granularity)
    qargs = _quantize_params(arg_params, qspecs)
    return qsym, qargs, dict(aux_params)


def quantize_net(network, calib_data, data_shape=None, calib_mode="naive",
                 num_calib_examples=None, excluded_layers=None, ctx=None,
                 logger=None, quantize_granularity="channel-wise"):
    """Quantize a (Hybrid)Block: export -> quantize_model -> SymbolBlock
    (parity: contrib/quantization.py quantize_net)."""
    import mxnet_tpu as mx
    from ..gluon import SymbolBlock

    if not isinstance(calib_data, mx.io.DataIter):
        calib_data = mx.io.NDArrayIter(calib_data, batch_size=min(
            32, calib_data.shape[0]), label_name=None)
    first = calib_data.provide_data[0]
    x = mx.nd.zeros(first.shape)
    network(x)  # materialize params
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        prefix = d + "/net"
        network.export(prefix, 0)
        sym, args, auxs = mx.model.load_checkpoint(prefix, 0)
        qsym, qargs, auxs = quantize_model(
            sym, args, auxs, data_names=(first.name,),
            calib_data=calib_data, calib_mode=calib_mode,
            num_calib_examples=num_calib_examples,
            excluded_sym_names=excluded_layers,
            quantize_granularity=quantize_granularity)
        # round-trip through the tested export format
        mx.model.save_checkpoint(prefix + "-q", 0, qsym, qargs, auxs)
        block = SymbolBlock.imports(prefix + "-q-symbol.json",
                                    [first.name],
                                    prefix + "-q-0000.params", ctx=ctx)
    return block
