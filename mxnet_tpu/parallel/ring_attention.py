"""Ring attention: sequence/context parallelism over the mesh 'sp' axis.

The reference (MXNet 1.x) predates long-context tech — SURVEY §5.7 documents
its absence and directs the rebuild to make SP first-class. This module
implements blockwise ring attention (Liu et al.'s ring schedule with
flash-style online-softmax accumulation):

  * sequence is sharded over the 'sp' mesh axis; each device holds a
    (B, H, S/n, D) block of q, k, v;
  * n ring steps: attend q-block against the resident k/v block, then
    `ppermute` k/v to the next neighbour over ICI — compute and transfer
    overlap, and no device ever materialises the full S x S score matrix;
  * numerically exact: running max/denominator accumulation is the fp-safe
    flash-attention recurrence.

Also exports `attention()` — the single-device fused softmax(qk)v used as
the reference implementation and as the building block for transformer
layers (parity role: contrib/transformer.cc interleaved selfatt ops).
"""
from __future__ import annotations

import functools
import math

__all__ = ["attention", "ring_attention", "ring_attention_sharded"]


def attention(q, k, v, causal=False, scale=None):
    """Plain fused attention on one device. q,k,v: (B, H, S, D) jax arrays."""
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ring_attention_local(q, k, v, axis_name, causal, scale):
    """Per-shard body (runs under shard_map): flash accumulation over the
    ring of k/v blocks."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my_idx * s_loc + jnp.arange(s_loc)  # global query positions

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        src = (my_idx - i) % n  # which shard this k/v block came from
        if causal:
            k_pos = src * s_loc + jnp.arange(k_cur.shape[2])
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, -jnp.inf)
        block_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, block_max)
        # guard fully-masked blocks: exp(-inf - -inf) -> use safe m
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    # initial carries must carry the sp-varying type (shard_map type system)
    from .._jax_compat import pcast

    o = pcast(jnp.zeros(q.shape, jnp.float32), axis_name, to="varying")
    m = pcast(jnp.full(q.shape[:-1], -jnp.inf, jnp.float32),
              axis_name, to="varying")
    l = pcast(jnp.zeros(q.shape[:-1], jnp.float32), axis_name,
              to="varying")
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o, m, l,
                                                   k.astype(jnp.float32),
                                                   v.astype(jnp.float32)))
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def ring_attention_sharded(mesh, axis="sp", causal=False, scale=None):
    """Build a shard_map'ed ring-attention callable over `mesh`.

    Returns fn(q, k, v) where inputs are (B, H, S, D) with S divisible by
    the sp axis size; inputs may be unsharded (they will be laid out).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .._jax_compat import get_shard_map

    shard_map = get_shard_map()

    jmesh = mesh.jax_mesh
    spec = P(None, None, axis, None)
    local = functools.partial(_ring_attention_local, axis_name=axis,
                              causal=causal, scale=scale)
    fn = shard_map(lambda q, k, v: local(q, k, v), mesh=jmesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(fn)


def ring_attention(q, k, v, mesh, axis="sp", causal=False, scale=None):
    """One-shot ring attention over NDArrays or jax arrays."""
    from ..ndarray import NDArray

    raw = lambda x: x._data if isinstance(x, NDArray) else x
    fn = ring_attention_sharded(mesh, axis=axis, causal=causal, scale=scale)
    out = fn(raw(q), raw(k), raw(v))
    return NDArray(out) if isinstance(q, NDArray) else out
