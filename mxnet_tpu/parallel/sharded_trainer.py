"""ShardedTrainer: the whole training step as ONE sharded XLA executable.

Replaces, in a single compiled computation laid out over a DeviceMesh, what
the reference spreads across per-GPU executors + kvstore:

  forward (DataParallelExecutorGroup.forward, executor_group.py:445)
  backward (:581)
  gradient allreduce (kvstore 'device': comm.h:503 Reduce + :598 Broadcast)
  optimizer update (fused update ops, optimizer_op.cc:49-970)
  BatchNorm running-stat writeback (aux state)

Gradients of replicated parameters computed from dp-sharded batches come out
of XLA as all-reduces over ICI; tp-sharded parameters get their activations
partitioned by GSPMD. Parameter/optimizer buffers are donated, so the update
is in-place at the XLA level (no 2x parameter memory).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from .. import autograd
from ..cached_op import TraceScope
from ..ndarray import NDArray
from .mesh import DeviceMesh

__all__ = ["ShardedTrainer", "sharding_rules"]


def sharding_rules(params, mesh: DeviceMesh) -> Dict[str, tuple]:
    """Default per-parameter PartitionSpecs (the group2ctx analogue).

    Everything is replicated except, when the mesh has a tp axis > 1,
    matmul/conv weights whose output dim divides tp — those are split on the
    output dimension (Megatron column parallel); GSPMD propagates the rest.
    """
    tp = mesh.size("tp")
    rules: Dict[str, tuple] = {}
    for name, p in params.items():
        shape = p.shape
        spec: tuple = ()
        if tp > 1 and shape and len(shape) >= 2 and shape[0] % tp == 0 \
                and name.endswith("weight"):
            spec = ("tp",) + (None,) * (len(shape) - 1)
        rules[name] = spec
    return rules


class ShardedTrainer:
    """Compiled data/tensor-parallel trainer over a DeviceMesh.

    Parameters
    ----------
    net : HybridBlock with materialized parameters.
    loss_fn : callable (pred NDArray, label NDArray) -> loss NDArray
        (e.g. a gluon loss block).
    optimizer : any registered optimizer name (the full 17-entry zoo:
        sgd/nag/signum/lars/lbsgd/sgld/dcasgd/adam/ftml/lamb/adagrad/
        rmsprop/adadelta/ftrl/adamax/nadam/test) or an Optimizer
        instance; the update math runs INSIDE the compiled step via
        opt_rules.py, reusing the ops/optimizer_op.py kernels.
        multi_precision=True keeps fp32 master weights for bf16 params.
    mesh : DeviceMesh (default: all devices on dp)
    rules : optional {param_name: PartitionSpec tuple} overriding defaults.
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh: Optional[DeviceMesh] = None, rules=None, donate=True,
                 zero=False, remat=False, accum_steps=1, nan_guard=True,
                 max_consecutive_skips=8):
        """Extra memory levers (all off by default, numerics unchanged):

        zero : ZeRO-1 — optimizer state lives dp-sharded (state memory
            divided by the dp size) and the update math runs sharded;
            only the parameter delta is all-gathered. Expressed as GSPMD
            sharding constraints, not manual collectives.
        remat : `jax.checkpoint` around the forward — backward
            recomputes activations instead of storing them (long-context
            / deep-model memory for FLOPs trade).
        accum_steps : gradient accumulation — the global batch is split
            into this many microbatches scanned inside the ONE compiled
            step (activation memory of one microbatch, numerics of the
            full batch for deterministic nets; stochastic layers like
            Dropout draw one rng key per microbatch, so their sample
            stream differs from the accum=1 run).

        Robustness levers:

        nan_guard : a non-finite loss or gradient SKIPS the whole update
            (params, optimizer state and aux are selected back to their
            pre-step values INSIDE the compiled step — one jnp.where per
            buffer, no extra transfers), so one bad batch cannot poison
            the run. Skips are counted (``skipped_steps`` /
            ``consecutive_skips``, and in the profiler when recording);
            after `max_consecutive_skips` skips in a row step() raises —
            a permanently diverged run must fail loudly, not spin.
            Reading the skip flag synchronizes the host with each step's
            completion; pass nan_guard=False to restore fully async
            dispatch when that latency matters more than the guard.
        """
        self._net = net
        self._loss_fn = loss_fn
        self._mesh = mesh or DeviceMesh()
        self._multiprocess = self._mesh.is_multiprocess
        self._donate = donate
        self._zero = bool(zero)
        self._remat = bool(remat)
        self._accum = int(accum_steps)
        if self._accum < 1:
            raise ValueError("accum_steps must be >= 1")
        self._nan_guard = bool(nan_guard)
        self._max_consecutive_skips = int(max_consecutive_skips)
        # multi-host dp gradient overlap: pin each gradient to a
        # dp-sharded layout (the ZeRO state layout) so XLA materializes
        # the cross-host grad sum as reduce-scatter + all-gather — which
        # the latency-hiding scheduler can overlap with backward — not
        # one monolithic all-reduce at the end of backward. Numerics
        # match up to XLA reduction order. MXNET_TPU_GRAD_SCATTER=0
        # opts out; ZeRO already implies the same layout.
        import os as _os

        self._grad_scatter = (
            self._multiprocess and self._mesh.size("dp") > 1
            and _os.environ.get("MXNET_TPU_GRAD_SCATTER", "1") != "0")
        self.skipped_steps = 0       # total updates skipped by the guard
        self.consecutive_skips = 0   # current skip streak
        opt_params = dict(optimizer_params or {})
        # lr_scheduler makes the learning rate a TRACED scalar argument
        # of the compiled step (one executable, lr varies per call)
        self._lr_scheduler = opt_params.pop("lr_scheduler", None)
        self._lr = float(opt_params.pop("learning_rate", 0.01))
        # the eager optimizer instance validates hyper-params and is the
        # static hyper source for the compiled update rule (opt_rules.py)
        from .. import optimizer as _opt_mod
        from .opt_rules import RULES

        if isinstance(optimizer, _opt_mod.Optimizer):
            self._opt = optimizer
            if opt_params:
                # hypers live on the instance; silently ignoring leftovers
                # would train with different dynamics than requested
                raise ValueError(
                    "optimizer_params other than learning_rate/"
                    "lr_scheduler cannot be combined with an Optimizer "
                    f"instance: {sorted(opt_params)}")
            # honour the instance's own lr/scheduler unless explicitly
            # overridden through optimizer_params
            if "learning_rate" not in (optimizer_params or {}):
                self._lr = float(self._opt.lr)
            if self._lr_scheduler is None and \
                    self._opt.lr_scheduler is not None:
                self._lr_scheduler = self._opt.lr_scheduler
        else:
            try:
                self._opt = _opt_mod.create(
                    optimizer, learning_rate=self._lr, **opt_params)
            except TypeError as e:
                raise ValueError(
                    f"unsupported optimizer params for {optimizer!r}: "
                    f"{e}") from None
        if self._lr_scheduler is not None:
            # same contract as Optimizer: learning_rate seeds the
            # scheduler's base_lr (optimizer/optimizer.py:41) — AFTER the
            # instance branch may have adopted the instance's lr
            self._lr_scheduler.base_lr = self._lr
        self._opt_name = type(self._opt).__name__.lower()
        if self._opt_name not in RULES:
            raise ValueError(
                f"no compiled update rule for optimizer "
                f"{self._opt_name!r}; available: {sorted(RULES)}")
        self._rule = RULES[self._opt_name]
        if self._opt_name == "lbsgd" and self._opt.batch_scale > 1 \
                and self._accum == 1:
            import warnings

            warnings.warn(
                "LBSGD batch_scale>1: the compiled step applies the "
                "large-batch lr warmup every step but does NOT "
                "accumulate gradients — pass accum_steps (or feed the "
                "full macro-batch) for the accumulation half",
                stacklevel=2)
        self._wd = float(self._opt.wd)

        params = net.collect_params()
        self._param_names = []
        self._train_handles: List[NDArray] = []
        self._aux_names = []
        self._aux_handles: List[NDArray] = []
        for name, p in params.items():
            if p._data is None:
                raise ValueError(
                    f"Parameter {name!r} not initialized; run one forward "
                    "pass (or initialize with explicit shapes) first")
            if p.grad_req != "null":
                self._param_names.append(name)
                self._train_handles.append(p.data())
            else:
                self._aux_names.append(name)
                self._aux_handles.append(p.data())
        self._rules = dict(sharding_rules(params, self._mesh))
        if rules:
            self._rules.update(rules)
        # distributed-correctness pre-check (analysis.distcheck pass 1):
        # a rule naming an absent axis would otherwise SILENTLY replicate
        # in _place_params below — fail here, param-named, with
        # did-you-mean hints (MXNET_TPU_DISTCHECK=0 opts out)
        from ..analysis import distcheck as _distcheck

        self._distcheck = _distcheck.enabled()
        if self._distcheck:
            names = self._param_names + self._aux_names
            handles = self._train_handles + self._aux_handles
            check_rules = {n: self._rules.get(n, ()) for n in names}
            for n, spec in self._rules.items():
                # user rules naming no parameter are dead — keep them in
                # the checked set so the typo gets a did-you-mean hint
                check_rules.setdefault(n, spec)
            _distcheck.run(
                rules=check_rules,
                shapes={n: tuple(h.shape)
                        for n, h in zip(names, handles)},
                mesh=self._mesh, churn=False)
        self._wd_mult = [1.0 if (n.endswith("weight") or n.endswith("gamma"))
                         else 0.0 for n in self._param_names]
        self._opt_raws = self._init_opt_state()
        self._step_fn = None
        self._t = 0
        # elasticity plumbing: the manager/epoch of the newest checkpoint,
        # so a preemption drain (or watchdog abort) can write a final one
        self._ckpt_manager = None
        self._ckpt_epoch = 0
        # model-bus publishing (publish_to): armed, every K-th successful
        # step streams a versioned weight record into the bus directory
        self._bus = None
        self._bus_every = 1
        self._bus_rollback = True
        self._bus_model = None
        self._bus_topk = None
        self.published_versions = []
        self._place_params()
        # one env var (MXNET_TPU_PREEMPT) arms graceful SIGTERM drains
        from .. import preempt as _preempt

        _preempt.maybe_install_from_env()

    # ------------------------------------------------------------ set-up ---

    def _global_put(self, host_arr, sh):
        """Multi-host-safe placement under a prebuilt NamedSharding."""
        return self._mesh.global_put(host_arr, sharding=sh)

    def _put_batch(self, raw, sh):
        """Lay a data batch out under `sh`. Multi-host: the caller passes
        its PROCESS-LOCAL portion of the global batch (the standard SPMD
        data-loading contract — each worker loads its own slice); the
        global batch is the concatenation over processes."""
        import jax

        if not self._multiprocess:
            return jax.device_put(raw, sh)
        if sh.is_fully_replicated:
            # per-rank slices would become INCONSISTENT replicas of one
            # "global" array and silently drift the hosts apart
            raise ValueError(
                "multi-host batch placement needs a process-spanning "
                "batch ('dp') axis in the mesh; this mesh replicates "
                "the batch — add a dp axis, or feed every process the "
                "identical batch via jax.device_put yourself")
        return jax.make_array_from_process_local_data(
            sh, _np.asarray(jax.device_get(raw)))

    @property
    def learning_rate(self):
        """Current (scheduled) lr — parity: optimizer.py learning_rate
        property, which consults the scheduler at the current step."""
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler(self._t))
        return self._lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.set_learning_rate(lr)

    def set_learning_rate(self, lr):
        """Change the lr mid-training (gluon Trainer parity, including
        the UserWarning raised when a scheduler already drives the lr —
        optimizer.py set_learning_rate). The lr is a traced argument of
        the compiled step, so no recompilation."""
        if self._lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already "
                              "been defined.")
        self._lr = float(lr)

    def _spec_for(self, name):
        return self._mesh.sharding(*self._rules.get(name, ()))

    def _dp_sharded_full(self, spec, shape):
        """`spec` additionally dp-sharded on the first divisible
        unsharded dim (no divisible dim: unchanged, the constraint is a
        no-op) — the ZeRO-1 state layout AND the grad reduce-scatter
        layout."""
        dp = self._mesh.size("dp")
        full = spec + (None,) * (len(shape) - len(spec))
        if dp > 1 and "dp" not in full:
            for i, (s, d) in enumerate(zip(full, shape)):
                if s is None and d % dp == 0:
                    full = full[:i] + ("dp",) + full[i + 1:]
                    break
        return full

    def _state_spec_for(self, name, shape):
        """Optimizer-state layout: the parameter's own spec, or — under
        ZeRO — additionally dp-sharded on the first divisible unsharded
        dim, dividing state memory by the dp size (ZeRO-1)."""
        # trim to the state's own rank: scalar states (e.g. Nadam's
        # momentum schedule) of a tp-sharded weight stay replicated
        spec = tuple(self._rules.get(name, ()))[:len(shape)]
        if not self._zero:
            return self._mesh.sharding(*spec)
        return self._mesh.sharding(*self._dp_sharded_full(spec, shape))

    def _grad_spec_for(self, name, shape):
        """Gradient reduce-scatter layout (``_grad_scatter``): dp-shard
        the gradient like ZeRO shards state, so the cross-host grad sum
        lowers to reduce-scatter + all-gather instead of one blocking
        all-reduce."""
        spec = tuple(self._rules.get(name, ()))[:len(shape)]
        return self._mesh.sharding(*self._dp_sharded_full(spec, shape))

    def _place_params(self):
        """Lay parameters out on the mesh per the rules (replicate or
        tp-shard) — the device_put that replaces per-GPU weight copies.
        Multi-host meshes go through _global_put (each process
        contributes its addressable shards of the same full copy)."""
        for name, h in zip(self._param_names, self._train_handles):
            h._rebind(self._global_put(h._data, self._spec_for(name)))
        for name, h in zip(self._aux_names, self._aux_handles):
            h._rebind(self._global_put(h._data, self._mesh.replicated()))
        self._opt_raws = tuple(
            tuple(self._global_put(s, self._state_spec_for(name, s.shape))
                  for s in per)
            for name, per in zip(self._param_names, self._opt_raws))

    def _is_lowp(self, raw):
        return str(raw.dtype) in ("bfloat16", "float16")

    def _init_opt_state(self):
        """Per-parameter state from the rule's factory. Under
        multi-precision an fp32 master copy is PREPENDED to each low-
        precision parameter's state and the rule's own state is built in
        fp32 (parity: create_state_multi_precision)."""
        import jax.numpy as jnp

        mp = getattr(self._opt, "multi_precision", False)
        out = []
        for h in self._train_handles:
            w = h._data
            if mp and self._is_lowp(w):
                w32 = jnp.asarray(w, jnp.float32)
                out.append((w32,) + self._rule.init(self._opt, w32))
            else:
                out.append(self._rule.init(self._opt, w))
        return tuple(out)

    # ------------------------------------------------------------- build ---
    def _service_token(self, kind):
        """Process-stable identity of the compiled step for the unified
        compile service (mxnet_tpu.compile): everything the trace BAKES
        into the executable that the aval signature cannot see — network
        structure (gluon repr), loss, optimizer rule + scalar hypers, wd
        schedule, sharding rules and the memory/robustness levers."""
        import hashlib

        hypers = tuple(sorted(
            (k, v) for k, v in vars(self._opt).items()
            if isinstance(v, (int, float, bool, str, type(None)))))
        from .. import kernels as _kernels

        blob = "\n".join([
            repr(self._net), repr(self._loss_fn), self._opt_name,
            repr(hypers), repr(self._wd), repr(self._wd_mult),
            repr(tuple(self._param_names)), repr(tuple(self._aux_names)),
            repr(sorted(self._rules.items())),
            repr(self._mesh.describe()),
            repr((self._donate, self._zero, self._remat, self._accum,
                  self._nan_guard, self._grad_scatter)),
            # kernel-dispatch identity: a retuned table or a flipped
            # MXNET_TPU_KERNELS must not reuse an executable traced
            # under the old routing
            _kernels.token_salt()])
        return ("trainer", kind,
                hashlib.sha1(blob.encode()).hexdigest()[:16])

    def warmup(self, x, y):
        """AOT warmup: build + compile the step executable for batches
        shaped like ``x``/``y`` (NDArray, jax array, or
        ``jax.ShapeDtypeStruct``) WITHOUT running a step — the pod
        cold-start hook. Registering the step with the compile service
        also replays any pending warmup-manifest entries recorded by a
        previous run, so every previously-seen batch signature compiles
        (or disk-loads) here rather than at first traffic."""
        x_raw = x._data if isinstance(x, NDArray) else x
        y_raw = y._data if isinstance(y, NDArray) else y
        if self._step_fn is None:
            if self._distcheck:
                # same pre-compile sharding surface check step() runs
                from ..analysis import distcheck as _dc

                _dc.check_trainer(self, x_raw, y_raw)
            self._step_fn = self._build(x_raw, y_raw)
        from .. import compile as _compile

        return _compile.warmup()

    def aot_lower(self, x, y):
        """AOT-lower the full train step under GSPMD for batches shaped
        like ``x``/``y`` WITHOUT executing it (and without consuming the
        RNG stream) — the compile-cleanliness proof for a training
        config before hardware is available (``__graft_entry__``'s
        multichip dryrun lowers the flagship dp×tp+ZeRO+remat config
        through this). Returns the jax ``Lowered``; ``.compile()``
        finishes the XLA pipeline and its HLO text feeds
        ``analysis.distcheck.schedule_from_hlo`` for the collective
        census."""
        import jax

        from .. import random as _rand

        x_raw = x._data if isinstance(x, NDArray) else x
        y_raw = y._data if isinstance(y, NDArray) else y
        if self._step_fn is None:
            if self._distcheck:
                from ..analysis import distcheck as _dc

                _dc.check_trainer(self, x_raw, y_raw)
            self._step_fn = self._build(x_raw, y_raw)
        _rand._ensure()
        key = _rand._state.key  # aval only; the stream does not advance

        def aval(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        import jax.numpy as jnp

        return self._step_fn.lower(
            tuple(aval(h._data) for h in self._train_handles),
            tuple(tuple(aval(s) for s in per) for per in self._opt_raws),
            tuple(aval(h._data) for h in self._aux_handles),
            jax.ShapeDtypeStruct(tuple(x_raw.shape),
                                 _np.dtype(x_raw.dtype)),
            jax.ShapeDtypeStruct(tuple(y_raw.shape),
                                 _np.dtype(y_raw.dtype)),
            aval(key), jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32))

    def _build(self, x_raw, y_raw):
        import jax
        import jax.numpy as jnp

        net = self._net
        loss_fn = self._loss_fn
        train_handles = self._train_handles
        aux_handles = self._aux_handles
        wd = self._wd
        wd_mult = self._wd_mult
        opt = self._opt
        rule = self._rule
        multi_precision = getattr(opt, "multi_precision", False)
        is_lowp = self._is_lowp
        n_aux = len(aux_handles)

        def run_net(praws, araws, x, y, rng):
            saved = [(h, h._data) for h in train_handles + aux_handles]
            scope = TraceScope(rng)
            try:
                for h, r in zip(train_handles, praws):
                    h._data = r
                for h, r in zip(aux_handles, araws):
                    h._data = r
                with scope, autograd.pause(train_mode=True):
                    out = net.forward(NDArray(x))
                    loss = loss_fn(out, NDArray(y)).mean()
                updates = {id(h): raw for h, raw in scope.state_updates}
                new_aux = tuple(updates.get(id(h), r)
                                for h, r in zip(aux_handles, araws))
                return loss._data, new_aux
            finally:
                for h, orig in saved:
                    h._data = orig

        if self._remat:
            # trade FLOPs for memory: backward re-derives activations
            run_net = jax.checkpoint(run_net)
        accum = self._accum
        zero = self._zero
        # ZeRO-1: the state layout each param's update math is pinned to
        state_sh = [self._state_spec_for(n, h._data.shape)
                    for n, h in zip(self._param_names, train_handles)]

        def grads_of(praws, araws, x, y, rng):
            """(loss, new_aux), grads for the FULL batch — directly, or
            accumulated over `accum` scanned microbatches (activation
            memory of one microbatch, numerics of the whole batch)."""
            if accum == 1:
                return jax.value_and_grad(run_net, has_aux=True)(
                    praws, araws, x, y, rng)
            b = x.shape[0]
            if b % accum:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum}")
            dp = self._mesh.size("dp")
            if (b // accum) % dp:
                import warnings

                warnings.warn(
                    f"microbatch size {b // accum} not divisible by the "
                    f"dp size {dp}: some devices idle every scan step — "
                    "accumulation should trade memory for time, not "
                    "parallelism", stacklevel=3)
            xs = x.reshape((accum, b // accum) + x.shape[1:])
            ys = y.reshape((accum, b // accum) + y.shape[1:])
            # keep each microbatch dp-sharded after the fold
            xs = jax.lax.with_sharding_constraint(
                xs, self._mesh.sharding(
                    *((None, "dp") + (None,) * (len(x.shape) - 1))))
            rngs = jax.random.split(rng, accum)

            def micro(carry, inp):
                g_acc, loss_acc, araws_c = carry
                xm, ym, rm = inp
                (l, new_aux), g = jax.value_and_grad(
                    run_net, has_aux=True)(praws, araws_c, xm, ym, rm)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                return (g_acc, loss_acc + l, new_aux), None

            init = (jax.tree_util.tree_map(jnp.zeros_like, praws),
                    jnp.zeros((), jnp.float32), araws)
            (g_sum, loss_sum, new_aux), _ = jax.lax.scan(
                micro, init, (xs, ys, rngs))
            grads = jax.tree_util.tree_map(lambda g: g / accum, g_sum)
            return (loss_sum / accum, new_aux), grads

        nan_guard = self._nan_guard
        grad_scatter = self._grad_scatter
        grad_sh = [self._grad_spec_for(n, h._data.shape)
                   for n, h in zip(self._param_names, train_handles)] \
            if grad_scatter else None

        def step_fn(praws, opt_raws, araws, x, y, rng, t, lr):
            (loss, new_aux), grads = grads_of(praws, araws, x, y, rng)
            if nan_guard:
                # one fused all-finite reduction over loss + every grad;
                # the flag also gates the select-back below
                finite = jnp.isfinite(loss)
                for g in grads:
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
            else:
                finite = jnp.bool_(True)
            tt = t.astype(jnp.float32)
            new_p, new_opt = [], []
            for i, (w, g, st) in enumerate(zip(praws, grads, opt_raws)):
                pwd = wd * wd_mult[i]
                if zero:
                    # pin gradient (and hence the state and delta math) to
                    # the dp-sharded state layout; XLA all-gathers only
                    # the final parameter delta (ZeRO-1)
                    g = jax.lax.with_sharding_constraint(g, state_sh[i])
                elif grad_scatter:
                    # multi-host dp: the same dp-sharded pin on the grad
                    # alone — the cross-host sum becomes reduce-scatter
                    # (+ all-gather of the delta), overlappable with
                    # backward by the latency-hiding scheduler
                    g = jax.lax.with_sharding_constraint(g, grad_sh[i])
                rng_i = jax.random.fold_in(rng, i + 1)  # stochastic rules
                if multi_precision and is_lowp(w):
                    # fp32 master copy leads the state tuple; the rule
                    # runs entirely in fp32, params get the cast result
                    w32, inner = st[0], st[1:]
                    w32n, innern = rule.update(
                        opt, w32, g.astype(jnp.float32), inner, lr, pwd,
                        tt, rng_i)
                    new_p.append(w32n.astype(w.dtype))
                    new_opt.append((w32n,) + tuple(innern))
                else:
                    # keep update arithmetic in the param dtype
                    wn, stn = rule.update(
                        opt, w, g.astype(w.dtype), st, lr, pwd, tt, rng_i)
                    new_p.append(wn)
                    new_opt.append(tuple(stn))
            if nan_guard:
                # NaN/Inf step guard: select every buffer back to its
                # pre-step value when any grad (or the loss) is non-finite
                # — the update is skipped entirely, on device
                new_p = [jnp.where(finite, n, w)
                         for n, w in zip(new_p, praws)]
                new_opt = [tuple(jnp.where(finite, ns, s)
                                 for ns, s in zip(per_new, per_old))
                           for per_new, per_old in zip(new_opt, opt_raws)]
                new_aux = tuple(jnp.where(finite, na, a)
                                for na, a in zip(new_aux, araws))
            return tuple(new_p), tuple(new_opt), new_aux, loss, finite

        # shardings: batch over dp; params per rules; opt state reuses the
        # per-param state layout the update math is pinned to; aux replicated
        p_sh = tuple(self._spec_for(n) for n in self._param_names)
        # per-SLOT shardings: state slots can differ in rank from the
        # parameter (e.g. Nadam's scalar momentum schedule)
        opt_sh = tuple(
            tuple(self._state_spec_for(n, s.shape) for s in per)
            for n, per in zip(self._param_names, self._opt_raws))
        aux_sh = (self._mesh.replicated(),) * n_aux
        data_spec = ("dp",) + (None,) * (len(x_raw.shape) - 1)
        x_sh = self._mesh.sharding(*data_spec)
        y_sh = self._mesh.sharding("dp") if len(y_raw.shape) >= 1 \
            else self._mesh.replicated()
        rep = self._mesh.replicated()
        donate = (0, 1, 2) if self._donate else ()
        from .. import compile as _compile

        return _compile.jit(
            step_fn, site="trainer", token=self._service_token("step"),
            in_shardings=(p_sh, opt_sh, aux_sh, x_sh, y_sh, rep, rep,
                          rep),
            out_shardings=(p_sh, opt_sh, aux_sh, rep, rep),
            donate_argnums=donate)

    # -------------------------------------------------------------- step ---
    def step(self, x, y):
        """Run one compiled train step; returns the (replicated) loss.

        With ``nan_guard`` (the default) a step whose loss or gradients
        are non-finite leaves params/optimizer/aux untouched; after
        ``max_consecutive_skips`` such steps in a row a RuntimeError is
        raised (the step counter still advances on skipped steps — the
        step was attempted).

        With a ``trainer.step`` watchdog deadline armed
        (:mod:`mxnet_tpu.watchdog`) the whole step — dispatch, compile,
        and the nan_guard host read — is deadline-bounded: a wedged step
        writes a crash bundle and raises a catchable StallError (or
        checkpoints and aborts under ``action:abort``). NOTE the first
        step includes XLA compilation; size the deadline for it.

        Once a preemption drain has been requested
        (:mod:`mxnet_tpu.preempt` — SIGTERM received, or the ``preempt``
        fault mode fired) no NEW step may start: step raises
        :class:`~mxnet_tpu.preempt.DrainRequested` *before* dispatching,
        so the in-flight step is always the last one. Loops that poll
        ``preempt.requested()`` after each step drain before ever seeing
        the exception."""
        from .. import preempt as _preempt
        from .. import watchdog as _watchdog

        if _preempt.requested():
            raise _preempt.DrainRequested(_preempt.event())
        return _watchdog.sync("trainer.step",
                              lambda: self._step_impl(x, y),
                              label=f"step {self._t + 1}")

    def _step_impl(self, x, y):
        from ..telemetry import steps as _tsteps

        # per-step phase timeline (data-wait / h2d / compute / optimizer
        # / sync — docs/OBSERVABILITY.md): the record opens here, phases
        # accrue inside _step_exec, and a raising step (injected fault,
        # drain request, stall) abandons its partial record
        _tsteps.begin_step(self._t + 1)
        try:
            out = self._step_exec(x, y)
        except BaseException:
            _tsteps.abort()
            raise
        _tsteps.end_step(flops=self._step_flops(),
                         devices=self._mesh.num_devices)
        if self._bus is not None and self._t % self._bus_every == 0:
            self.publish_update()
        return out

    def _step_flops(self):
        """XLA-analyzed flops per invocation of the compiled step (the
        ``mfu_xla`` numerator), or None before the compile service has
        captured a cost analysis for it."""
        from ..telemetry import costs as _tcosts

        token = getattr(self._step_fn, "_token_key", None)
        return _tcosts.flops_for(token) if token is not None else None

    def step_report(self):
        """The most recent step's telemetry record: duration, phase
        split, and (once cost analysis is captured) ``flops`` +
        ``mfu_xla``. None before the first completed step (or with
        telemetry disabled)."""
        from ..telemetry import steps as _tsteps

        return _tsteps.last()

    def _step_exec(self, x, y):
        import time as _time

        import jax

        from .. import faults as _faults
        from .. import random as _rand
        from ..telemetry import steps as _tsteps

        x_raw = x._data if isinstance(x, NDArray) else x
        y_raw = y._data if isinstance(y, NDArray) else y
        if _faults.active():
            # 'trainer.step' injection: raise/delay/kill, or nan-poison
            # the batch (which the nan_guard must then absorb)
            x_raw = _faults.point("trainer.step", x_raw)
        if self._step_fn is None and self._distcheck:
            # distcheck auto-run BEFORE compile: full sharding surface
            # (params + optimizer-state layouts + batch dp divisibility)
            # — a misconfiguration fails here with a param-named Issue
            # list instead of an XLA error mid-compile
            from ..analysis import distcheck as _distcheck

            _distcheck.check_trainer(self, x_raw, y_raw)
        t0 = _time.perf_counter()
        x_raw = self._put_batch(
            x_raw, self._mesh.sharding(
                *(("dp",) + (None,) * (len(x_raw.shape) - 1))))
        y_raw = self._put_batch(y_raw, self._mesh.sharding("dp"))
        _tsteps.phase("h2d", (_time.perf_counter() - t0) * 1e3)
        if self._step_fn is None:
            self._step_fn = self._build(x_raw, y_raw)
        self._t += 1
        import jax.numpy as jnp

        lr = self._lr if self._lr_scheduler is None \
            else float(self._lr_scheduler(self._t))
        in_p = tuple(h._data for h in self._train_handles)
        in_opt = self._opt_raws
        in_aux = tuple(h._data for h in self._aux_handles)
        t0 = _time.perf_counter()
        new_p, new_opt, new_aux, loss, ok = self._step_fn(
            in_p, in_opt, in_aux,
            x_raw, y_raw, _rand.next_key(),
            jnp.asarray(self._t, jnp.int32),
            jnp.asarray(lr, jnp.float32))
        # the fused executable runs fwd+bwd+optimizer as one program, so
        # the optimizer phase is folded into compute (async dispatch:
        # device time lands in the nan-guard sync read below, or in the
        # next step's phases when nan_guard=False)
        _tsteps.phase("compute", (_time.perf_counter() - t0) * 1e3)
        if self._donate and self._distcheck:
            # donation-safety (distcheck pass 3): the step donated every
            # param/opt/aux input buffer — poison them so a stale alias
            # used later raises a param-named use-after-donate error
            # instead of jax's anonymous "Array has been deleted"
            from ..analysis import distcheck as _distcheck

            origin = "ShardedTrainer.step (donate=True)"
            for name, raw in zip(self._param_names, in_p):
                _distcheck.mark_donated(raw, name, origin, self._t)
            for name, per in zip(self._param_names, in_opt):
                for j, raw in enumerate(per):
                    _distcheck.mark_donated(
                        raw, f"{name} (optimizer state {j})", origin,
                        self._t)
            for name, raw in zip(self._aux_names, in_aux):
                _distcheck.mark_donated(raw, name, origin, self._t)
        with autograd.pause():
            for h, raw in zip(self._train_handles, new_p):
                h._data = raw  # donated buffers: rebind directly
            for h, raw in zip(self._aux_handles, new_aux):
                h._data = raw
        self._opt_raws = new_opt
        if self._nan_guard:
            t0 = _time.perf_counter()
            self._account_skip(bool(ok))  # blocks on step completion
            _tsteps.phase("sync", (_time.perf_counter() - t0) * 1e3)
        return NDArray(loss)

    def _account_skip(self, ok):
        from .. import profiler as _profiler

        if ok:
            self.consecutive_skips = 0
            return
        self.skipped_steps += 1
        self.consecutive_skips += 1
        _profiler.record_skip_step(self.skipped_steps,
                                   self.consecutive_skips)
        if self.consecutive_skips >= self._max_consecutive_skips:
            raise RuntimeError(
                f"ShardedTrainer: {self.consecutive_skips} consecutive "
                "steps produced non-finite loss/gradients and were "
                "skipped (step "
                f"{self._t}, {self.skipped_steps} skipped total) — the "
                "run has diverged; lower the learning rate, check the "
                "data pipeline, or resume from the last good checkpoint")

    def predict(self, x):
        """Compiled sharded inference forward (replicated output)."""
        import jax

        x_raw = x._data if isinstance(x, NDArray) else x
        x_raw = self._put_batch(
            x_raw, self._mesh.sharding(
                *(("dp",) + (None,) * (len(x_raw.shape) - 1))))
        if getattr(self, "_predict_fn", None) is None:
            net = self._net
            train_handles = self._train_handles
            aux_handles = self._aux_handles

            def fwd(praws, araws, x_):
                saved = [(h, h._data) for h in train_handles + aux_handles]
                try:
                    for h, r in zip(train_handles, praws):
                        h._data = r
                    for h, r in zip(aux_handles, araws):
                        h._data = r
                    with autograd.pause(train_mode=False):
                        out = net.forward(NDArray(x_))
                    return out._data
                finally:
                    for h, orig in saved:
                        h._data = orig

            p_sh = tuple(self._spec_for(n) for n in self._param_names)
            aux_sh = (self._mesh.replicated(),) * len(aux_handles)
            x_sh = self._mesh.sharding(
                *(("dp",) + (None,) * (len(x_raw.shape) - 1)))
            from .. import compile as _compile

            self._predict_fn = _compile.jit(
                fwd, site="trainer",
                token=self._service_token("predict"),
                in_shardings=(p_sh, aux_sh, x_sh),
                out_shardings=self._mesh.replicated())
        out = self._predict_fn(
            tuple(h._data for h in self._train_handles),
            tuple(h._data for h in self._aux_handles), x_raw)
        return NDArray(out)

    # -------------------------------------------------------- model bus ---
    def publish_to(self, bus, every=1, compress_threshold=None,
                   model=None, topk=None, rollback=True):
        """Stream live weight updates into a model bus: every `every`-th
        successful step publishes a version-stamped record of the
        current params (+ aux) into `bus` (a directory path or a
        :class:`~mxnet_tpu.modelbus.ModelBus`) for serving workers to
        apply between batches (docs/SERVING.md "Online updates").

        Small params ride as full tensors; params at or above
        `compress_threshold` elements ride int8 per-row compressed;
        `topk` ({param_name: k}) publishes only the k most-changed rows
        of the named (embedding-table-shaped) params. A non-finite
        update is never published (the nan-guard signal, re-checked at
        the bus). With `rollback` (default), a publish that finds the
        bus head quarantined by a subscriber first re-publishes the
        newest good version — the ROADMAP's "rollback = re-publish
        version N" contract.

        Returns the :class:`~mxnet_tpu.modelbus.ModelBus`.
        """
        from ..modelbus import ModelBus

        self._bus = bus if isinstance(bus, ModelBus) \
            else ModelBus(bus, compress_threshold=compress_threshold)
        self._bus_every = max(1, int(every))
        self._bus_rollback = bool(rollback)
        self._bus_model = model
        self._bus_topk = dict(topk) if topk else None
        return self._bus

    def publish_update(self):
        """Publish the current weights to the armed bus NOW (the per-K
        step hook calls this; explicit calls are fine too). Collective —
        every process gathers; only the writer rank writes. Returns the
        published version (None on non-writer ranks, a skipped
        non-finite update, or no armed bus)."""
        if self._bus is None:
            return None
        # host gathers are collective (ZeRO shards allgather) — run them
        # on EVERY process before the writer-rank gate
        params = [(n, self._host_copy(h._data))
                  for n, h in zip(self._param_names, self._train_handles)]
        aux = [(n, self._host_copy(h._data))
               for n, h in zip(self._aux_names, self._aux_handles)]
        if not self._is_writer_rank():
            return None
        if self._bus_rollback:
            self._bus.auto_rollback(worker="publisher")
        version = self._bus.publish(params, step=self._t, aux=aux,
                                    model=self._bus_model,
                                    topk=self._bus_topk)
        if version is not None:
            self.published_versions.append(version)
        return version

    # ------------------------------------------------------- checkpoint ---
    def _host_copy(self, arr):
        """Full host copy of a (possibly multi-host-sharded) array.
        Non-addressable shards (ZeRO state on other hosts) are gathered
        with a cross-process allgather."""
        import jax

        if getattr(arr, "is_fully_addressable", True) or \
                getattr(arr, "is_fully_replicated", False):
            return jax.device_get(arr)
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(arr, tiled=True)

    def _ckpt_keys(self):
        """Expected entry keys, POSITIONAL (collect_params order) so a
        fresh process with fresh gluon auto-prefixes can resume."""
        keys = ["__t__", "__rng_seed__", "__rng_key__", "__names__"]
        if self._lr_scheduler is not None:
            keys.append("__sched__")
        keys += [f"p{i}" for i in range(len(self._param_names))]
        keys += [f"a{i}" for i in range(len(self._aux_names))]
        for i, per in enumerate(self._opt_raws):
            keys += [f"s{i}_{j}" for j in range(len(per))]
        return keys

    def _state_payload(self):
        """Assemble the full checkpoint payload as {key: NDArray}. Runs
        COLLECTIVELY on every process (the host copies allgather); the
        caller decides which rank writes."""
        import jax
        import jax.numpy as jnp

        from .. import random as _rand

        _rand._ensure()
        names_blob = "\n".join(self._param_names + self._aux_names)
        payload = {
            "__t__": NDArray(jnp.asarray(self._t, jnp.int32)),
            "__rng_seed__": NDArray(
                jnp.asarray(_rand.current_seed(), jnp.int32)),
            "__rng_key__": NDArray(jnp.asarray(
                jax.device_get(_rand._state.key))),
            "__names__": NDArray(jnp.asarray(_np.frombuffer(
                names_blob.encode(), _np.uint8))),
        }
        if self._lr_scheduler is not None:
            # schedulers decay IN PLACE; resume must rewind their state
            import pickle

            payload["__sched__"] = NDArray(jnp.asarray(_np.frombuffer(
                pickle.dumps(self._lr_scheduler), _np.uint8)))
        for i, h in enumerate(self._train_handles):
            payload[f"p{i}"] = NDArray(self._host_copy(h._data))
        for i, h in enumerate(self._aux_handles):
            payload[f"a{i}"] = NDArray(self._host_copy(h._data))
        for i, per in enumerate(self._opt_raws):
            for j, s in enumerate(per):
                payload[f"s{i}_{j}"] = NDArray(self._host_copy(s))
        return payload

    def _is_writer_rank(self):
        """_host_copy's allgather is collective (every process runs it),
        but only one process may write a SHARED path; host-local
        trainers write regardless of rank."""
        import jax

        return not self._multiprocess or jax.process_index() == 0

    def save_states(self, fname):
        """Checkpoint params + optimizer state + step counter + the
        global RNG stream to one file in the `mx.nd.save` container
        (bf16 handled there as uint16 bits). Entries are positional,
        keyed by `collect_params()` order, so resuming into a freshly
        built identical architecture works even though gluon
        auto-prefixes differ between processes. The write is ATOMIC
        (tmp + fsync + os.replace): a run preempted mid-checkpoint
        leaves the previous state file intact, never a torn one.
        parity role: Trainer.save_states + model checkpoints
        (SURVEY §5.4)."""
        from ..checkpoint import atomic_write
        from ..ndarray import utils as nd_utils

        payload = self._state_payload()
        if self._is_writer_rank():
            atomic_write(fname, lambda tmp: nd_utils.save(tmp, payload))

    def topology_meta(self):
        """JSON-able topology record written into every checkpoint's
        MANIFEST entry (``meta.topology``): mesh shape, per-array
        sharding specs, and jax/device metadata. Arrays themselves are
        saved in CANONICAL HOST LAYOUT (full, gathered, C-order — see
        ``_host_copy``), so this record is *descriptive*: resume uses it
        to detect a topology change and reshard on load, never to
        interpret the bytes."""
        from .. import checkpoint as _ckpt

        return {
            "format": "canonical-host-v1",
            "mesh": self._mesh.describe(),
            "param_sharding": {n: list(self._rules.get(n, ()))
                               for n in self._param_names},
            "zero": self._zero,
            "host": _ckpt.host_metadata(),
        }

    def _remember_manager(self, manager, epoch, data_iter=None):
        """Track the newest manager/epoch (and the data iterator whose
        position rides in the checkpoint) and (re-)register the shared
        final-checkpoint hook (``watchdog.set_last_resort``) that both a
        watchdog ``action:abort`` and a preemption drain invoke. A hook
        the USER installed explicitly is never clobbered — only ours
        (tagged) is replaced as training advances."""
        from .. import watchdog as _watchdog

        self._ckpt_manager = manager
        self._ckpt_epoch = int(epoch)
        if data_iter is not None:
            self._ckpt_data_iter = data_iter
        prev = _watchdog.last_resort()
        if prev is None or getattr(prev, "_mxtpu_trainer_hook", False):
            hook = self._final_checkpoint
            try:
                hook.__func__._mxtpu_trainer_hook = True
            except AttributeError:
                pass
            _watchdog.set_last_resort(hook)

    def _final_checkpoint(self):
        """Last-resort/drain save: one more checkpoint through the
        remembered manager at epoch ``last+1`` with ``meta.drain`` set —
        the entry's ``step`` records the exact global step, which is the
        resume position for mid-epoch drains (data-position restore)."""
        mgr = self._ckpt_manager
        if mgr is None:
            return None
        from .. import preempt as _preempt

        meta = {"drain": _preempt.event() or True}
        return self.save_checkpoint(
            mgr, self._ckpt_epoch + 1, meta=meta,
            data_iter=getattr(self, "_ckpt_data_iter", None))

    def save_checkpoint(self, manager, epoch, meta=None, data_iter=None):
        """Write trainer state through a :class:`~mxnet_tpu.checkpoint.
        CheckpointManager` — atomic write, CRC-checksummed manifest entry,
        keep-N rotation, and a ``meta.topology`` record (mesh shape,
        per-array sharding specs, jax/device metadata) making the
        checkpoint topology-portable. Collective across processes; only
        the writer rank touches disk. Also registers this manager as the
        preemption-drain/last-resort target. Returns the manager's
        {name: path} map (None on non-writer ranks).

        ``data_iter``: an iterator with the ``state_dict()`` grammar
        (ImageRecordIter / TokenRecordIter / PrefetchingIter) — its exact
        stream position is recorded as ``meta.data_state`` and, once
        passed, rides in every later drain/last-resort checkpoint too, so
        a mid-epoch preemption resumes at the next unseen batch with the
        identical shuffle + augmentation stream."""
        from ..ndarray import utils as nd_utils

        payload = self._state_payload()
        meta = dict(meta or {})
        meta.setdefault("topology", self.topology_meta())
        if data_iter is not None and "data_state" not in meta:
            meta["data_state"] = data_iter.state_dict()
        self._remember_manager(manager, epoch, data_iter)
        if not self._is_writer_rank():
            return None
        return manager.save(
            epoch, {"states": lambda tmp: nd_utils.save(tmp, payload)},
            step=self._t, meta=meta)

    @staticmethod
    def _topology_changed(saved, current):
        """Human-readable mismatch list between two topology records
        (empty = bit-exact-resume territory)."""
        diffs = []
        sm, cm = saved.get("mesh") or {}, current.get("mesh") or {}
        if sm.get("axes") != cm.get("axes"):
            diffs.append(f"mesh axes {sm.get('axes')} -> {cm.get('axes')}")
        if sm.get("num_devices") != cm.get("num_devices"):
            diffs.append(f"device count {sm.get('num_devices')} -> "
                         f"{cm.get('num_devices')}")
        sh, ch = saved.get("host") or {}, current.get("host") or {}
        if sh.get("process_count") != ch.get("process_count"):
            diffs.append(f"process count {sh.get('process_count')} -> "
                         f"{ch.get('process_count')}")
        return diffs

    def resume(self, manager, reshard=None, data_iter=None):
        """Restore the latest good checkpoint recorded by `manager`
        (corrupt files are detected by checksum and skipped in favour of
        the previous good epoch). Returns the manifest entry — epoch,
        step, meta — or None when the manager records no checkpoint yet
        (fresh start).

        Topology portability: the entry's ``meta.topology`` is compared
        against this trainer's mesh. On a MATCH the restore is bit-exact
        (same arrays, same layout, same RNG stream). On a MISMATCH the
        checkpoint — stored in canonical host layout — is **resharded on
        load**: every array (params, aux, and sharded/ZeRO optimizer
        state) is re-placed through THIS mesh's sharding rules, the RNG
        stream continues from the saved position (keys are host-side and
        fold in step/param indices, never device ids, so the sample
        stream is device-count independent), and the entry's ``step`` is
        the data position to resume from. Numerics then match the
        uninterrupted run up to XLA reduction-order differences — not
        bit-exact. Pass ``reshard=False`` (or set
        ``MXNET_TPU_PREEMPT_RESHARD=0``) to forbid cross-topology resume;
        a mismatch then raises a mesh-naming ValueError."""
        import os as _os

        res = manager.resume()
        if res is None:
            return None
        entry, paths = res
        saved_topo = (entry.get("meta") or {}).get("topology")
        if saved_topo:
            current = self.topology_meta()
            diffs = self._topology_changed(saved_topo, current)
            if diffs:
                if reshard is None:
                    reshard = _os.environ.get(
                        "MXNET_TPU_PREEMPT_RESHARD", "1") != "0"
                saved_mesh = (saved_topo.get("mesh") or {}).get("axes")
                if not reshard:
                    # name the axes precisely: a typo'd axis on the new
                    # mesh gets a did-you-mean hint + the valid axis list
                    # (the shared difflib helper via mesh.axis_error)
                    axis_notes = "".join(
                        "; saved " + self._mesh.axis_error(a)
                        for a in sorted(saved_mesh or {})
                        if a not in self._mesh.axis_sizes)
                    raise ValueError(
                        f"checkpoint epoch {entry['epoch']} was written on "
                        f"DeviceMesh({saved_mesh}) but this trainer runs on "
                        f"{self._mesh!r} ({'; '.join(diffs)}{axis_notes}) "
                        "and resharding "
                        "is disabled — resume on the original topology, or "
                        "allow resharding (reshard=True / unset "
                        "MXNET_TPU_PREEMPT_RESHARD=0) to re-place the "
                        "canonical-layout arrays on the new mesh")
                import warnings

                warnings.warn(
                    f"resuming checkpoint epoch {entry['epoch']} across a "
                    f"topology change ({'; '.join(diffs)}): arrays reshard "
                    f"from DeviceMesh({saved_mesh}) onto {self._mesh!r}; "
                    "numerics match the original trajectory up to XLA "
                    "reduction order (bit-exact only on the saved "
                    "topology)", stacklevel=2)
        self.load_states(paths["states"])
        data_state = (entry.get("meta") or {}).get("data_state")
        if data_iter is not None and data_state is not None:
            # restore the exact stream position the checkpoint was cut at
            # — load_state_dict re-partitions it when this gang's
            # num_parts differs from the saving gang's (resharded resume)
            data_iter.load_state_dict(data_state)
        self._remember_manager(manager, entry["epoch"],
                               data_iter=data_iter)
        return entry

    def load_states(self, fname):
        """Restore a `save_states` checkpoint, re-laying every tensor out
        on this trainer's mesh (mesh/rules/ZeRO layout may differ from
        the saving run — resharding is just a fresh device_put). Also
        restores the global RNG stream, so a resumed run reproduces the
        uninterrupted run's sample stream exactly. The key set AND every
        tensor shape are validated before anything is mutated — a failed
        load never leaves the trainer half-restored."""
        import os

        import jax

        from .. import random as _rand
        from ..ndarray import utils as nd_utils

        if not os.path.exists(fname):
            raise FileNotFoundError(
                f"trainer state file not found: {fname!r}")
        try:
            arrays = nd_utils.load(fname)
        except Exception as e:
            raise ValueError(
                f"corrupt trainer state file {fname!r}: "
                f"{type(e).__name__}: {e} (truncated write? load through "
                "CheckpointManager.resume to fall back to the previous "
                "good checkpoint)") from e
        expected = set(self._ckpt_keys())
        got = set(arrays)
        if expected != got:
            raise ValueError(
                "checkpoint does not match this trainer: missing "
                f"{sorted(expected - got)[:5]}, unexpected "
                f"{sorted(got - expected)[:5]} (param count or optimizer "
                "differs)")
        shape_of = {}
        for i, h in enumerate(self._train_handles):
            shape_of[f"p{i}"] = tuple(h._data.shape)
        for i, h in enumerate(self._aux_handles):
            shape_of[f"a{i}"] = tuple(h._data.shape)
        for i, per in enumerate(self._opt_raws):
            for j, s in enumerate(per):
                shape_of[f"s{i}_{j}"] = tuple(s.shape)
        bad = [(k, tuple(arrays[k].shape), want)
               for k, want in shape_of.items()
               if tuple(arrays[k].shape) != want]
        if bad:
            k, got_s, want_s = bad[0]
            raise ValueError(
                f"checkpoint does not match this trainer: entry {k!r} "
                f"has shape {got_s}, trainer expects {want_s} "
                f"(saved param order: "
                f"{bytes(_np.asarray(arrays['__names__']._data)).decode()})")

        def take(key, want_dtype, spec):
            # _global_put handles multi-host meshes (plain device_put
            # cannot target non-addressable devices)
            return self._global_put(
                arrays[key]._data.astype(want_dtype), spec)

        self._t = int(arrays["__t__"].asscalar())
        if self._lr_scheduler is not None:
            import pickle

            self._lr_scheduler = pickle.loads(
                bytes(_np.asarray(arrays["__sched__"]._data)))
        _rand._ensure()
        _rand._state.seed = int(arrays["__rng_seed__"].asscalar())
        _rand._state.key = arrays["__rng_key__"]._data
        for i, (name, h) in enumerate(zip(self._param_names,
                                          self._train_handles)):
            h._rebind(take(f"p{i}", h._data.dtype, self._spec_for(name)))
        for i, h in enumerate(self._aux_handles):
            h._rebind(take(f"a{i}", h._data.dtype, self._mesh.replicated()))
        self._opt_raws = tuple(
            tuple(take(f"s{i}_{j}", s.dtype,
                       self._state_spec_for(name, s.shape))
                  for j, s in enumerate(per))
            for i, (name, per) in enumerate(zip(self._param_names,
                                                self._opt_raws)))

    def unshard(self, ctx=None):
        """Gather parameters back to one device for eager/export use."""
        import jax

        from ..context import current_context

        dev = (ctx or current_context()).jax_device()
        for h in self._train_handles + self._aux_handles:
            h._rebind(jax.device_put(self._host_copy(h._data), dev))

    @property
    def mesh(self):
        return self._mesh
