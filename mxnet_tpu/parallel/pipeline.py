"""Pipeline parallelism (pp axis): GPipe-style microbatch pipelining.

Beyond the reference: MXNet 1.x only offers manual `group2ctx` placement
for model parallelism; this module provides real pipeline scheduling the
TPU way — no per-stage processes, no send/recv framework. The whole
pipeline is ONE jitted SPMD program: each device on the ``pp`` mesh axis
holds one stage's parameters (stacked pytree, leading dim = stages),
activations flow stage-to-stage with `lax.ppermute` over ICI, and the
skewed schedule is a `lax.scan` over M + S - 1 ticks (M microbatches
through S stages — the GPipe fill/drain schedule). The program is fully
differentiable, so `jax.grad` through it yields pipeline-parallel
BACKWARD for free (XLA reverses the ppermutes).

Constraint (standard for SPMD pipelining): every stage must have the same
input/output shape and the same parameter structure — the "stack of
identical blocks" regime of transformer LMs. Embed/head layers live
outside the pipelined region.

    stages_params = stack_stage_params([blk.collect_params() ...])
    fn = pipeline_apply(stage_fn, mesh, num_microbatches=8)
    y = fn(stages_params, x)   # == sequential application of all stages
"""
from __future__ import annotations

__all__ = ["pipeline_apply", "stack_stage_params"]


def _check_stacked_leading_dim(stacked_params, n, what):
    """Trace-time validation: every leaf's leading dim must equal the
    mesh-axis size (a 2n-stage stack would silently use every other
    slice via p[0]). Raises (not assert — `-O` must not strip it)."""
    import jax

    leaves = jax.tree_util.tree_leaves(stacked_params)
    if not leaves:
        raise ValueError(f"stacked {what} params are empty")
    lead = {p.shape[0] for p in leaves}
    if lead != {n}:
        raise ValueError(
            f"stacked {what} params have leading dims {sorted(lead)}; "
            f"the {what} axis has {n} devices")


def stack_stage_params(param_trees):
    """Stack S identical-structure parameter pytrees along a new leading
    axis (the pp-sharded dimension)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_apply(stage_fn, mesh, num_microbatches, axis="pp"):
    """Build the pipelined callable.

    Parameters
    ----------
    stage_fn : (params_slice, x) -> y with ``y.shape == x.shape``; one
        stage's computation as a pure function.
    mesh : DeviceMesh with a ``pp`` (or `axis`) dimension.
    num_microbatches : microbatches the global batch is split into; must
        divide the batch size. More microbatches = smaller pipeline
        bubble (bubble fraction = (S-1)/(M+S-1)).

    Returns
    -------
    fn(stacked_params, x) -> y — jit-compiled SPMD program. x is the
    FULL batch (B, ...); stacked_params has leading dim S (sharded over
    the pp axis).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .._jax_compat import get_shard_map

    shard_map = get_shard_map()

    jmesh = mesh.jax_mesh
    num_stages = mesh.size(axis)
    m = num_microbatches

    def local(params, xs):
        # params: this stage's slice, leading dim 1 -> squeeze
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        # mark the carries as device-varying over pp (shard_map's vma check
        # rejects a scan whose carry changes variance mid-loop)
        from .._jax_compat import pcast

        state = pcast(jnp.zeros_like(xs[0]), axis, to="varying")
        out_buf = pcast(jnp.zeros_like(xs), axis, to="varying")

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t (while it exists); other stages
            # consume the activation ppermuted in from the previous stage
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, feed, state)
            y = stage_fn(params, inp)
            # last stage banks microbatch t-(S-1) when it is in range
            out_idx = t - (num_stages - 1)
            write = (stage == num_stages - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(out_idx, 0, m - 1), 0)
            out_buf = jnp.where(write, updated, out_buf)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, out_buf), None

        (state, out_buf), _ = jax.lax.scan(
            tick, (state, out_buf), jnp.arange(m + num_stages - 1))
        # results live on the last stage; replicate them across pp
        out_buf = jnp.where(stage == num_stages - 1, out_buf,
                            jnp.zeros_like(out_buf))
        return jax.lax.psum(out_buf, axis)

    sharded = shard_map(local, mesh=jmesh,
                        in_specs=(P(axis), P()), out_specs=P())

    @jax.jit
    def run(stacked_params, x):
        _check_stacked_leading_dim(stacked_params, num_stages, "pp")
        b = x.shape[0]
        if b % m:
            raise ValueError(
                f"batch {b} not divisible by microbatches {m}")
        xs = x.reshape((m, b // m) + x.shape[1:])
        out = sharded(stacked_params, xs)
        return out.reshape((b,) + out.shape[2:])

    return run
