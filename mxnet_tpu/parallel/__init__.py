"""Parallelism layer: device meshes + sharded compiled training steps.

Role parity: this subsumes the reference's multi-device execution stack —
`DataParallelExecutorGroup` (`python/mxnet/module/executor_group.py:144`,
batch split `decide_slices` :282), KVStore `device` gradient reduction
(`src/kvstore/comm.h:503` merge-buffer + ElementwiseSum), and the
`group2ctx` model-parallel placement (`src/executor/graph_executor.cc:1044`).

TPU-native design (the scaling-book recipe): pick a Mesh, annotate
shardings, let XLA insert collectives.

  * `DeviceMesh` — named axes over `jax.devices()`: dp (data), tp (tensor),
    pp (pipeline stages), sp (sequence/context). The reference's per-GPU
    executor list becomes ONE jitted computation laid out over the mesh.
  * sharding rules — per-parameter PartitionSpecs (replicated under dp;
    split output/input dims under tp), the GSPMD analogue of `group2ctx`.
  * `ShardedTrainer` — the whole training step (forward, loss, backward,
    optimizer update, BatchNorm stat update) compiled into ONE XLA
    executable with donated parameter buffers. Cross-device gradient
    reduction is emitted by XLA as all-reduces over ICI — replacing
    kvstore 'device' mode's copy-to-merge-buffer/ElementwiseSum/broadcast
    round trip (`src/kvstore/kvstore_local.h:239`).

Single-chip users win too: the per-step Python/dispatch overhead of the
imperative Trainer collapses into one executable launch.
"""
from __future__ import annotations

from .mesh import DeviceMesh, current_mesh
from .moe import moe_apply, stack_expert_params
from .pipeline import pipeline_apply, stack_stage_params
from .ring_attention import attention, ring_attention, ring_attention_sharded
from .sharded_trainer import ShardedTrainer, sharding_rules

__all__ = ["DeviceMesh", "current_mesh", "ShardedTrainer", "sharding_rules",
           "attention", "ring_attention", "ring_attention_sharded",
           "pipeline_apply", "stack_stage_params", "moe_apply",
           "stack_expert_params"]
