"""Expert parallelism (ep axis): mixture-of-experts layer.

Beyond the reference (MXNet 1.x has no MoE): experts are partitioned
across the ``ep`` mesh axis — each device owns one expert's parameters
(stacked pytree, leading dim = experts) — inside ONE jitted SPMD program.
Top-1 routing follows the Switch-Transformer recipe: a linear router
scores tokens, each token goes to its argmax expert, the expert output is
scaled by the router probability (keeps routing differentiable), and a
load-balancing auxiliary loss penalizes expert collapse.

Combine strategy: each device computes its expert on the full token set
masked to its assignment, and a `psum` over ep merges the disjoint
results — the dense-dispatch formulation, which on TPU is one all-reduce
over ICI and no host-side gather/scatter. (All-to-all token dispatch is a
bandwidth optimization of the same math for when experts dominate
compute.)

    fn = moe_apply(expert_fn, mesh)
    y, aux_loss = fn(stacked_expert_params, router_w, x)
"""
from __future__ import annotations

__all__ = ["moe_apply", "stack_expert_params"]

from .pipeline import _check_stacked_leading_dim
from .pipeline import stack_stage_params as stack_expert_params


def moe_apply(expert_fn, mesh, axis="ep"):
    """Build the expert-parallel MoE callable.

    Parameters
    ----------
    expert_fn : (params_slice, x) -> y — one expert, same output shape.
    mesh : DeviceMesh with an ``ep`` axis; its size = number of experts.

    Returns
    -------
    fn(stacked_params, router_w, x) -> (y, aux_loss) where x is (N, d),
    router_w is (d, E), y is (N, d_out); aux_loss is the Switch
    load-balancing term (scalar, add it to the training loss).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .._jax_compat import get_shard_map

    shard_map = get_shard_map()

    jmesh = mesh.jax_mesh
    num_experts = mesh.size(axis)

    def local(params, router_w, x):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        e = jax.lax.axis_index(axis)
        logits = x @ router_w                       # (N, E) replicated
        probs = jax.nn.softmax(logits, axis=-1)
        assigned = jnp.argmax(probs, axis=-1)       # (N,)
        mine = (assigned == e)                      # (N,) this device's tokens
        gate = jnp.where(mine, jnp.max(probs, axis=-1), 0.0)  # (N,)
        y = expert_fn(params, x)                    # (N, d_out)
        y = y * gate[:, None]
        y = jax.lax.psum(y, axis)                   # disjoint merge
        # Switch aux loss: E * sum_e fraction_e * mean_prob_e — each device
        # contributes its own expert's f_e * P_e term, summed over ep
        frac_e = jnp.mean(mine.astype(jnp.float32))
        mean_p_e = jnp.mean(probs, axis=0)[e]
        aux = num_experts * jax.lax.psum(frac_e * mean_p_e, axis)
        return y, aux

    sharded = shard_map(local, mesh=jmesh,
                        in_specs=(P(axis), P(), P()),
                        out_specs=(P(), P()))

    @jax.jit
    def run(stacked_params, router_w, x):
        _check_stacked_leading_dim(stacked_params, num_experts, "ep")
        if router_w.shape[-1] != num_experts:
            # silently-dropped experts otherwise: tokens routed past
            # column E match no device and psum to zero rows
            raise ValueError(
                f"router_w has {router_w.shape[-1]} expert columns but "
                f"the ep axis has {num_experts} devices")
        y, aux = sharded(stacked_params, router_w, x)
        return y, jnp.reshape(aux, ())

    return run
