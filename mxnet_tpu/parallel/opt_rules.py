"""Per-optimizer update rules for the compiled ShardedTrainer step.

Bridges the eager optimizer zoo (``optimizer/optimizer.py``, 17 entries —
parity: python/mxnet/optimizer/optimizer.py) into the ONE-executable
sharded train step. Each rule supplies

  init(opt, w)                         -> tuple of fresh state buffers
  update(opt, w, g, st, lr, wd, t, rng) -> (new_w, new_states)

reusing the jitted kernels from ``ops/optimizer_op.py`` (parity:
src/operator/optimizer_op.cc:49-970) so the compiled step and the eager
Trainer produce identical numerics. Hyper-parameters are read from the
eager Optimizer instance at trace time (static, baked into the
executable); ``lr`` and ``t`` arrive as traced float32 scalars so lr
schedules and bias-correction never retrace; ``rng`` feeds stochastic
rules (SGLD).

Rule contract details:
- ``g`` arrives in the update arithmetic dtype (the weight dtype, or
  float32 under multi-precision — ShardedTrainer handles the master-copy
  wrapping before calling the rule).
- Rules that scale ``lr`` by traced-``t`` factors compute the effective
  lr in float32, then ``_lr_of`` casts it to the weight dtype so bf16
  parameters are never silently promoted.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import optimizer_op as K

__all__ = ["RULES", "Rule"]

RULES = {}


class Rule:
    def __init__(self, init, update):
        self.init = init
        self.update = update


def _register(names, init, update):
    for n in names:
        RULES[n] = Rule(init, update)


def _zeros(w, n):
    return tuple(jnp.zeros(w.shape, w.dtype) for _ in range(n))


def _clip(opt):
    return opt.clip_gradient if opt.clip_gradient else -1.0


def _lr_of(lr, w):
    return lr.astype(w.dtype) if hasattr(lr, "astype") else lr


def _prep(opt, g, w, wd, with_wd=False):
    """SGD/SGLD-family gradient prep: rescale, clip, THEN optionally add
    wd*w (the eager SGLD ordering)."""
    g = g * opt.rescale_grad
    if opt.clip_gradient:
        g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
    return g + wd * w if with_wd else g


def _prep_wd_then_clip(opt, g, w, wd):
    """Adam-family prep: wd*w folded in BEFORE the clip (eager Adamax/
    Nadam ordering, same as ops.optimizer_op._prep_grad_wd)."""
    g = g * opt.rescale_grad + wd * w
    if opt.clip_gradient:
        g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
    return g


def _mom_init(opt, w):
    return _zeros(w, 1) if opt.momentum else ()


# ------------------------------------------------------------ SGD family ---

def _sgd_update(opt, w, g, st, lr, wd, t, rng):
    kw = dict(lr=_lr_of(lr, w), wd=wd, rescale_grad=opt.rescale_grad,
              clip_gradient=_clip(opt))
    if opt.momentum:
        # fused Pallas step (registry family opt_sgd) where the dispatch
        # table proved it; the XLA baseline is sgd_mom_update itself, so
        # routing is numerics-neutral (bit-exact contract under jit)
        from .. import kernels as _kernels

        w2, m2 = _kernels.dispatch(
            "opt_sgd", w, g, st[0], kw["lr"], momentum=opt.momentum,
            wd=wd, rescale_grad=opt.rescale_grad,
            clip_gradient=_clip(opt))
        return w2, (m2,)
    return K.sgd_update.fn(w, g, **kw), ()


def _nag_update(opt, w, g, st, lr, wd, t, rng):
    kw = dict(lr=_lr_of(lr, w), wd=wd, rescale_grad=opt.rescale_grad,
              clip_gradient=_clip(opt))
    if opt.momentum:
        w2, m2 = K.nag_mom_update.fn(w, g, st[0], momentum=opt.momentum,
                                     **kw)
        return w2, (m2,)
    return K.sgd_update.fn(w, g, **kw), ()


def _signum_update(opt, w, g, st, lr, wd, t, rng):
    kw = dict(lr=_lr_of(lr, w), wd=wd, rescale_grad=opt.rescale_grad,
              clip_gradient=_clip(opt))
    if opt.momentum:
        w2, m2 = K.signum_update.fn(w, g, st[0], momentum=opt.momentum,
                                    wd_lh=opt.wd_lh, **kw)
        return w2, (m2,)
    return K.signsgd_update.fn(w, g, **kw), ()


def _lars_update(opt, w, g, st, lr, wd, t, rng):
    kw = dict(lr=_lr_of(lr, w), eta=opt.eta, epsilon=opt.epsilon, wd=wd,
              rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    if opt.momentum:
        w2, m2 = K.lars_sgd_mom_update.fn(w, g, st[0],
                                          momentum=opt.momentum, **kw)
        return w2, (m2,)
    return K.lars_sgd_update.fn(w, g, **kw), ()


def _sgld_update(opt, w, g, st, lr, wd, t, rng):
    g = _prep(opt, g, w, wd, with_wd=True)
    lr_w = _lr_of(lr, w)
    noise = jax.random.normal(rng, w.shape, w.dtype) * jnp.sqrt(lr_w)
    return w - lr_w / 2 * g + noise, ()


def _lbsgd_update(opt, w, g, st, lr, wd, t, rng):
    """LBSGD warmup multiplier from traced t. The eager optimizer's
    batch_scale gradient accumulation is subsumed by ShardedTrainer's
    accum_steps (one compiled scan); rules see per-step gradients."""
    nwup = float(opt.warmup_epochs * opt.updates_per_epoch)
    maxmult = float(opt.batch_scale)
    if opt.warmup_strategy == "lars":
        # trust ratio from the RAW gradient (eager _get_lars gets the
        # unrescaled accumulated grad); the step uses the prepped one
        w2s = jnp.sum(jnp.square(w))
        g2s = jnp.sum(jnp.square(g))
        mult = jnp.clip(jnp.sqrt(w2s / (g2s + wd * w2s + 1e-18)),
                        0.01, 100.0)
        g = _prep(opt, g, w, wd)
        step = (_lr_of(lr, w) * mult.astype(w.dtype)) * (g + wd * w)
        if opt.momentum:
            m2 = opt.momentum * st[0] - step
            return w + m2, (m2,)
        return w - step, ()
    tt = t + float(opt.init_updates)
    if nwup <= 1:
        # eager _get_lbmult: nup >= nwup wins first, so a zero/one-step
        # warmup window means the full batch_scale multiplier from the
        # first update
        mult = jnp.float32(maxmult)
    else:
        if opt.warmup_strategy == "linear":
            mult = 1.0 + (maxmult - 1) * tt / nwup
        elif opt.warmup_strategy == "power2":
            mult = 1.0 + (maxmult - 1) * (tt * tt) / (nwup * nwup)
        elif opt.warmup_strategy == "sqrt":
            mult = 1.0 + (maxmult - 1) * jnp.sqrt(tt / nwup)
        else:
            mult = jnp.float32(1.0)
        mult = jnp.where(tt >= nwup, maxmult, mult)
    kw = dict(lr=_lr_of(lr * mult, w), wd=wd,
              rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    if opt.momentum:
        w2, m2 = K.sgd_mom_update.fn(w, g, st[0], momentum=opt.momentum,
                                     **kw)
        return w2, (m2,)
    return K.sgd_update.fn(w, g, **kw), ()


def _dcasgd_init(opt, w):
    prev = jnp.array(w)
    return (_zeros(w, 1) + (prev,)) if opt.momentum else (prev,)


def _dcasgd_update(opt, w, g, st, lr, wd, t, rng):
    g = _prep(opt, g, w, wd)
    prev = st[-1]
    lr_w = _lr_of(lr, w)
    delta = -lr_w * (g + wd * w + opt.lamda * g * g * (w - prev))
    if opt.momentum:
        m2 = opt.momentum * st[0] + delta
        return w + m2, (m2, w)
    return w + delta, (w,)


# ----------------------------------------------------------- Adam family ---

def _adam_update(opt, w, g, st, lr, wd, t, rng):
    # bias correction folded into lr (reference Adam semantics); the
    # fused Pallas step (family opt_adam) routes by dispatch table with
    # adam_update as its bit-exact XLA baseline
    lr_eff = lr * jnp.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
    from .. import kernels as _kernels

    w2, m2, v2 = _kernels.dispatch(
        "opt_adam", w, g, st[0], st[1], _lr_of(lr_eff, w),
        beta1=opt.beta1, beta2=opt.beta2, epsilon=opt.epsilon, wd=wd,
        rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    return w2, (m2, v2)


def _ftml_update(opt, w, g, st, lr, wd, t, rng):
    w2, d2, v2, z2 = K.ftml_update.fn(
        w, g, st[0], st[1], st[2], lr=_lr_of(lr, w), beta1=opt.beta1,
        beta2=opt.beta2, epsilon=opt.epsilon, wd=wd,
        rescale_grad=opt.rescale_grad, clip_grad=_clip(opt), t=t)
    return w2, (d2, v2, z2)


def _lamb_update(opt, w, g, st, lr, wd, t, rng):
    upd, m2, v2 = K.lamb_update_phase1.fn(
        w, g, st[0], st[1], beta1=opt.beta1, beta2=opt.beta2,
        epsilon=opt.epsilon, t=t, bias_correction=opt.bias_correction,
        wd=wd, rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    r1 = jnp.sqrt(jnp.sum(jnp.square(w)))
    r2 = jnp.sqrt(jnp.sum(jnp.square(upd)))
    w2 = K.lamb_update_phase2.fn(
        w, upd, r1, r2, lr=_lr_of(lr, w),
        lower_bound=opt.lower_bound if opt.lower_bound else -1.0,
        upper_bound=opt.upper_bound if opt.upper_bound else -1.0)
    return w2, (m2, v2)


def _adagrad_update(opt, w, g, st, lr, wd, t, rng):
    w2, h2 = K.adagrad_update.fn(
        w, g, st[0], lr=_lr_of(lr, w), epsilon=opt.float_stable_eps,
        wd=wd, rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    return w2, (h2,)


def _rmsprop_init(opt, w):
    return _zeros(w, 3 if opt.centered else 1)


def _rmsprop_update(opt, w, g, st, lr, wd, t, rng):
    kw = dict(lr=_lr_of(lr, w), gamma1=opt.gamma1, epsilon=opt.epsilon,
              wd=wd, rescale_grad=opt.rescale_grad,
              clip_gradient=_clip(opt),
              clip_weights=opt.clip_weights if opt.clip_weights else -1.0)
    if opt.centered:
        w2, n2, g2, d2 = K.rmspropalex_update.fn(
            w, g, st[0], st[1], st[2], gamma2=opt.gamma2, **kw)
        return w2, (n2, g2, d2)
    w2, n2 = K.rmsprop_update.fn(w, g, st[0], **kw)
    return w2, (n2,)


def _adadelta_update(opt, w, g, st, lr, wd, t, rng):
    w2, a2, d2 = K.adadelta_update.fn(
        w, g, st[0], st[1], rho=opt.rho, epsilon=opt.epsilon, wd=wd,
        rescale_grad=opt.rescale_grad, clip_gradient=_clip(opt))
    return w2, (a2, d2)


def _ftrl_update(opt, w, g, st, lr, wd, t, rng):
    w2, z2, n2 = K.ftrl_update.fn(
        w, g, st[0], st[1], lr=_lr_of(lr, w), lamda1=opt.lamda1,
        beta=opt.beta, wd=wd, rescale_grad=opt.rescale_grad,
        clip_gradient=_clip(opt))
    return w2, (z2, n2)


def _adamax_update(opt, w, g, st, lr, wd, t, rng):
    g = _prep_wd_then_clip(opt, g, w, wd)
    m2 = opt.beta1 * st[0] + (1.0 - opt.beta1) * g
    u2 = jnp.maximum(opt.beta2 * st[1], jnp.abs(g))
    lr_eff = _lr_of(lr / (1.0 - opt.beta1 ** t), w)
    return w - lr_eff * m2 / (u2 + 1e-8), (m2, u2)


def _nadam_init(opt, w):
    # third slot: the cumulative momentum schedule, carried PER PARAMETER
    # (the eager reference shares one m_schedule float across all params,
    # an order-dependent wart; per-param is the faithful per-tensor math
    # and matches eager exactly for the t-th update of each param trained
    # every step)
    return _zeros(w, 2) + (jnp.ones((), jnp.float32),)


def _nadam_update(opt, w, g, st, lr, wd, t, rng):
    g = _prep_wd_then_clip(opt, g, w, wd)
    psi = opt.schedule_decay
    mom_t = opt.beta1 * (1.0 - 0.5 * 0.96 ** (t * psi))
    mom_t1 = opt.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1.0) * psi))
    sched = st[2] * mom_t
    sched_next = sched * mom_t1
    m2 = opt.beta1 * st[0] + (1.0 - opt.beta1) * g
    v2 = opt.beta2 * st[1] + (1.0 - opt.beta2) * g * g
    g_prime = g / (1.0 - sched).astype(w.dtype)
    m_prime = m2 / (1.0 - sched_next).astype(w.dtype)
    v_prime = v2 / (1.0 - opt.beta2 ** t).astype(w.dtype)
    m_bar = ((1.0 - mom_t).astype(w.dtype) * g_prime
             + mom_t1.astype(w.dtype) * m_prime)
    w2 = w - _lr_of(lr, w) * m_bar / (jnp.sqrt(v_prime) + opt.epsilon)
    return w2, (m2, v2, sched)


def _test_update(opt, w, g, st, lr, wd, t, rng):
    w2 = w - g * opt.rescale_grad * _lr_of(lr, w)
    return w2, (w2,)


_register(["sgd"], _mom_init, _sgd_update)
_register(["nag"], _mom_init, _nag_update)
_register(["signum", "signsgd"], _mom_init, _signum_update)
_register(["lars"], _mom_init, _lars_update)
_register(["sgld"], lambda opt, w: (), _sgld_update)
_register(["lbsgd"], _mom_init, _lbsgd_update)
_register(["dcasgd"], _dcasgd_init, _dcasgd_update)
_register(["adam"], lambda opt, w: _zeros(w, 2), _adam_update)
_register(["ftml"], lambda opt, w: _zeros(w, 3), _ftml_update)
_register(["lamb"], lambda opt, w: _zeros(w, 2), _lamb_update)
_register(["adagrad"], lambda opt, w: _zeros(w, 1), _adagrad_update)
_register(["rmsprop"], _rmsprop_init, _rmsprop_update)
_register(["adadelta"], lambda opt, w: _zeros(w, 2), _adadelta_update)
_register(["ftrl"], lambda opt, w: _zeros(w, 2), _ftrl_update)
_register(["adamax"], lambda opt, w: _zeros(w, 2), _adamax_update)
_register(["nadam"], _nadam_init, _nadam_update)
_register(["test"], lambda opt, w: _zeros(w, 1), _test_update)
