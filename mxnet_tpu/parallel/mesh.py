"""Device mesh management.

The mesh replaces the reference's explicit device lists (`ctx=[mx.gpu(i) for
i in ...]` handed to Module/Trainer). Axis names follow the scaling-book
convention: dp (data), tp (tensor/model), pp (pipeline), sp (sequence/
context), ep (experts). Unused axes have size 1 so sharding rules can always
reference them.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["DeviceMesh", "current_mesh"]

_tls = threading.local()

AXIS_ORDER = ("dp", "pp", "tp", "sp", "ep")


class DeviceMesh:
    """A named-axis mesh over jax devices.

    Examples
    --------
    DeviceMesh()                      # all devices on the dp axis
    DeviceMesh({"dp": 4, "tp": 2})    # 8 devices, 4-way data x 2-way tensor
    """

    def __init__(self, axes: Optional[Dict[str, int]] = None, devices=None):
        import jax
        import numpy as np

        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        n = len(self.devices)
        if axes is None:
            axes = {"dp": n}
        sizes = dict(axes)
        for a, v in sizes.items():
            if not isinstance(a, str) or not a:
                raise ValueError(
                    f"mesh axis names must be non-empty strings, got "
                    f"{a!r}; conventional axes: {list(AXIS_ORDER)}")
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"mesh axis {a!r} must have a positive integer size, "
                    f"got {v!r}")
        prod = 1
        for v in sizes.values():
            prod *= v
        if prod > n:
            raise ValueError(
                f"mesh axes {sizes} require {prod} devices, have {n}")
        self.devices = self.devices[:prod]  # smaller meshes use a prefix
        # canonical axis order so PartitionSpecs are stable
        self.axis_names = tuple(a for a in AXIS_ORDER if a in sizes) + tuple(
            a for a in sizes if a not in AXIS_ORDER)
        self.axis_sizes = {a: sizes[a] for a in self.axis_names}
        shape = tuple(self.axis_sizes[a] for a in self.axis_names)
        dev_array = np.array(self.devices).reshape(shape)
        self._jax_mesh = jax.sharding.Mesh(dev_array, self.axis_names)

    @property
    def jax_mesh(self):
        return self._jax_mesh

    def size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    def axis_error(self, axis) -> str:
        """Mesh-naming diagnostic for an axis this mesh does not have:
        did-you-mean suggestion (shared difflib helper) + the valid axis
        list. Used by the distcheck sharding verifier and the resume/
        reshard error paths so every mesh-naming error hints the same
        way."""
        from ..base import did_you_mean

        return (f"axis {axis!r} is not an axis of this mesh"
                f"{did_you_mean(axis, self.axis_names)}; valid axes: "
                f"{list(self.axis_names)}")

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def sharding(self, *spec):
        """A NamedSharding for a PartitionSpec over this mesh. Axis names not
        present in the mesh are treated as replicated (None)."""
        import jax

        P = jax.sharding.PartitionSpec
        clean = tuple(s if (s is None or s in self.axis_names) else None
                      for s in spec)
        return jax.sharding.NamedSharding(self._jax_mesh, P(*clean))

    def replicated(self):
        import jax

        return jax.sharding.NamedSharding(self._jax_mesh,
                                          jax.sharding.PartitionSpec())

    def describe(self):
        """JSON-able topology descriptor — axis sizes + device census —
        recorded in checkpoint MANIFESTs (``meta.topology.mesh``) so a
        resume can detect, name, and reshard across topology changes."""
        return {"axes": dict(self.axis_sizes),
                "num_devices": self.num_devices,
                "process_indices": sorted({getattr(d, "process_index", 0)
                                           for d in self.devices})}

    @property
    def is_multiprocess(self) -> bool:
        """True when this mesh spans devices of other processes
        (multi-host SPMD under jax.distributed)."""
        import jax

        me = jax.process_index()
        return any(d.process_index != me for d in self.devices)

    def global_put(self, host_arr, *spec, sharding=None):
        """Lay a host-resident FULL array out over this mesh as a global
        array, multi-host included: on a process-spanning mesh every
        process holds the same full copy and contributes its addressable
        shards (`make_array_from_callback`). This is how stacked
        pipeline/expert params and replicated weights reach a multi-host
        mesh — a plain device_put cannot target non-addressable
        devices. Pass either a PartitionSpec tuple (*spec) or a prebuilt
        NamedSharding (sharding=)."""
        import jax

        sh = sharding if sharding is not None else (
            self.sharding(*spec) if spec else self.replicated())
        if not self.is_multiprocess:
            return jax.device_put(host_arr, sh)
        import numpy as np

        host_np = np.asarray(jax.device_get(host_arr))
        return jax.make_array_from_callback(
            host_np.shape, sh, lambda idx: host_np[idx])

    def __enter__(self):
        if not hasattr(_tls, "stack"):
            _tls.stack = []
        _tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()

    def __repr__(self):
        return f"DeviceMesh({self.axis_sizes})"


def current_mesh() -> Optional[DeviceMesh]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None
