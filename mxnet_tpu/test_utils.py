"""Testing utilities.

Parity target: `python/mxnet/test_utils.py` — the reference's central test
harness: `assert_almost_equal` (:664, dtype-aware tolerances),
`check_numeric_gradient` (:1101, central finite differences vs autograd),
`check_consistency` (:1546, run the same graph on a list of contexts and
cross-assert outputs & grads), `default_context` (:58), `rand_ndarray`.

TPU translation: contexts compared are cpu vs tpu (or multiple virtual cpu
devices); numeric grads are checked against the imperative tape AND against
`jax.grad` on the hybridized path.
"""
from __future__ import annotations

import os

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal", "same",
    "almost_equal", "rand_ndarray", "rand_shape_nd", "rand_shape_2d",
    "rand_shape_3d", "check_numeric_gradient", "check_consistency",
    "environment", "default_dtype", "simple_forward", "numeric_grad",
]

_default_ctx = None


def default_context() -> Context:
    """Env-switched default test context (parity: test_utils.py:58,
    MXNET_TEST_DEVICE)."""
    global _default_ctx
    if _default_ctx is not None:
        return _default_ctx
    dev = os.environ.get("MXNET_TEST_DEVICE", "")
    if dev:
        name, _, idx = dev.partition(":")
        _default_ctx = Context(name, int(idx or 0))
    else:
        _default_ctx = current_context()
    return _default_ctx


def set_default_context(ctx: Context):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def _dtype_tol(*arrays):
    """Default (rtol, atol) scaled by the loosest dtype involved (parity:
    test_utils.py default_tols)."""
    tol = {np.dtype(np.float16): (1e-2, 1e-2),
           np.dtype(np.float32): (1e-4, 1e-5),
           np.dtype(np.float64): (1e-6, 1e-8)}
    rtol, atol = 1e-4, 1e-5
    for a in arrays:
        dt = getattr(a, "dtype", None)
        if dt is None:
            continue
        if str(dt) == "bfloat16":
            rtol, atol = max(rtol, 2e-2), max(atol, 2e-2)
            continue
        r, t = tol.get(np.dtype(dt), (1e-4, 1e-5))
        rtol, atol = max(rtol, r), max(atol, t)
    return rtol, atol


def _to_numpy(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b) -> bool:
    return np.array_equal(_to_numpy(a), _to_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _to_numpy(a), _to_numpy(b)
    if rtol is None or atol is None:
        drtol, datol = _dtype_tol(a, b)
        rtol = drtol if rtol is None else rtol
        atol = datol if atol is None else atol
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    an, bn = _to_numpy(a), _to_numpy(b)
    if rtol is None or atol is None:
        drtol, datol = _dtype_tol(an, bn)
        rtol = drtol if rtol is None else rtol
        atol = datol if atol is None else atol
    an64 = an.astype(np.float64)
    bn64 = bn.astype(np.float64)
    if np.allclose(an64, bn64, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    err = np.abs(an64 - bn64)
    denom = np.maximum(np.abs(bn64), atol / max(rtol, 1e-300))
    rel = err / np.maximum(denom, 1e-300)
    idx = np.unravel_index(np.argmax(rel), rel.shape) if rel.size else ()
    raise AssertionError(
        f"Arrays {names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}: "
        f"max abs err {err.max() if err.size else 0:.3g}, max rel err "
        f"{rel.max() if rel.size else 0:.3g} at {idx}: "
        f"{names[0]}={an64[idx] if err.size else None} "
        f"{names[1]}={bn64[idx] if err.size else None}")


# ------------------------------------------------------------- random -------

def rand_shape_nd(ndim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(np.random.randint(low, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return rand_shape_nd(2, max(dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return rand_shape_nd(3, max(dim0, dim1, dim2))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None,
                 scale=1.0):
    if stype != "default":
        from .ndarray import sparse

        return sparse.rand_sparse_ndarray(shape, stype, density=density,
                                          dtype=dtype, ctx=ctx)
    data = np.random.uniform(-scale, scale, size=shape)
    return nd.array(data, ctx=ctx or default_context(), dtype=dtype or np.float32)


# ------------------------------------------------- numeric gradient ---------

def numeric_grad(f, inputs, eps=1e-3):
    """Central finite differences of scalar-valued f w.r.t. each np input."""
    grads = []
    for i, x in enumerate(inputs):
        x = np.asarray(x, dtype=np.float64)
        g = np.zeros_like(x)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(*[inp if k != i else x for k, inp in enumerate(inputs)]))
            flat[j] = orig - eps
            fm = float(f(*[inp if k != i else x for k, inp in enumerate(inputs)]))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(op_name, input_arrays, kwargs=None, rtol=1e-2,
                           atol=1e-3, eps=1e-3):
    """Check the autograd tape's gradient of sum(op(*inputs)) against central
    finite differences (parity: test_utils.py:1101 check_numeric_gradient).

    Runs under locally-scoped x64 so the finite differences are computed in
    real float64 without changing suite-wide dtype semantics."""
    from ._jax_compat import enable_x64

    with enable_x64():
        _check_numeric_gradient_x64(op_name, input_arrays, kwargs, rtol, atol, eps)


def _check_numeric_gradient_x64(op_name, input_arrays, kwargs, rtol, atol, eps):
    from . import autograd

    kwargs = kwargs or {}
    nds = [nd.array(np.asarray(a, dtype=np.float64), dtype=np.float64)
           for a in input_arrays]
    for x in nds:
        x.attach_grad()
    with autograd.record():
        out = nd.invoke(op_name, *nds, **kwargs)
        if isinstance(out, tuple):
            out = out[0]
        loss = out.sum()
    loss.backward()
    sym_grads = [x.grad.asnumpy() for x in nds]

    def f(*np_inputs):
        arrs = [nd.array(a, dtype=np.float64) for a in np_inputs]
        o = nd.invoke(op_name, *arrs, **kwargs)
        if isinstance(o, tuple):
            o = o[0]
        return o.sum().asscalar()

    num_grads = numeric_grad(f, [np.asarray(a, dtype=np.float64)
                                 for a in input_arrays], eps=eps)
    for i, (s, n) in enumerate(zip(sym_grads, num_grads)):
        assert_almost_equal(s, n, rtol=rtol, atol=atol,
                            names=(f"autograd_grad[{i}]", f"numeric_grad[{i}]"))


def check_consistency(fn, input_shapes, ctx_list=None, dtypes=None, rtol=None,
                      atol=None, grad=True):
    """Run `fn(*NDArrays)` on every (ctx, dtype) combination and cross-assert
    outputs (+ grads) against the first one (parity: test_utils.py:1546).

    On a single-platform host "contexts" are cpu devices 0..n; on TPU it
    compares cpu vs tpu — same idea as the reference's cpu-vs-gpu fixture.
    """
    from . import autograd

    if ctx_list is None:
        ctx_list = [cpu(0), default_context()]
    if dtypes is None:
        dtypes = [np.float32]
    base_np = [np.random.uniform(-1, 1, size=s) for s in input_shapes]
    ref_out = ref_grads = None
    for ctx in ctx_list:
        for dtype in dtypes:
            nds = [nd.array(a, ctx=ctx, dtype=dtype) for a in base_np]
            if grad:
                for x in nds:
                    x.attach_grad()
                with autograd.record():
                    out = fn(*nds)
                    loss = out.sum()
                loss.backward()
                grads = [x.grad.asnumpy() for x in nds]
            else:
                out = fn(*nds)
                grads = []
            o = out.asnumpy()
            if ref_out is None:
                ref_out, ref_grads = o, grads
            else:
                assert_almost_equal(o, ref_out, rtol=rtol, atol=atol,
                                    names=(f"out@{ctx}/{np.dtype(dtype).name}", "ref"))
                for i, (g, rg) in enumerate(zip(grads, ref_grads)):
                    assert_almost_equal(g, rg, rtol=rtol, atol=atol,
                                        names=(f"grad[{i}]@{ctx}", "ref"))
    return ref_out


def simple_forward(op_name, *np_inputs, **kwargs):
    out = nd.invoke(op_name, *[nd.array(a) for a in np_inputs], **kwargs)
    if isinstance(out, tuple):
        return tuple(o.asnumpy() for o in out)
    return out.asnumpy()


class environment:
    """Context manager patching environment variables (parity:
    test_utils.py `with environment(...)`)."""

    def __init__(self, *args):
        if len(args) == 2:
            self._vars = {args[0]: args[1]}
        else:
            self._vars = dict(args[0])
        self._saved = {}

    def __enter__(self):
        for k, v in self._vars.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
