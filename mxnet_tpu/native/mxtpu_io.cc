// Native IO runtime: RecordIO scanning + image batch normalization.
//
// Parity role: the reference's C++ data-pipeline hot paths —
// dmlc-core's RecordIOReader (src/io/image_recordio.h framing) and the
// image normalization inner loops of iter_image_recordio_2.cc
// (ImageRecordIOParser2<DType>::ProcessImage). The Python framework
// binds these through ctypes (no pybind11 in the image); everything here
// is plain C ABI.
//
// Build: g++ -O3 -shared -fPIC (see native/build.py; rebuilt on demand,
// cached next to this source).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

static const uint32_t kMagic = 0xced7230a;
static const uint32_t kLRecBits = 29;

// Scan a RecordIO file for magic-framed records. Fills caller-provided
// arrays (capacity `cap`) with each record's payload offset and length.
// Returns the number of records found, or -1 on IO error, or -(needed)
// if cap was too small (caller retries with a larger buffer).
long long mxtpu_recordio_scan(const char* path, uint64_t* offsets,
                              uint64_t* lengths, long long cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  long long n = 0;
  uint32_t header[2];
  for (;;) {
    long pos = ftell(f);
    size_t got = fread(header, sizeof(uint32_t), 2, f);
    if (got != 2) break;  // EOF
    if (header[0] != kMagic) { fclose(f); return -1; }
    uint64_t len = header[1] & ((1u << kLRecBits) - 1);
    uint32_t cflag = header[1] >> kLRecBits;
    if (cflag != 0) {
      // multi-part records: skip continuation framing (rare; the
      // Python path handles them; report as unsupported)
      fclose(f);
      return -1;
    }
    if (n >= cap) { fclose(f); return -(n + 1); }
    offsets[n] = (uint64_t)pos + 2 * sizeof(uint32_t);
    lengths[n] = len;
    ++n;
    uint64_t padded = (len + 3u) & ~3ull;
    if (fseek(f, (long)(pos + 8 + (long)padded), SEEK_SET) != 0) break;
  }
  fclose(f);
  return n;
}

// Read `count` records' payloads (given offsets/lengths from scan) into
// one contiguous buffer `dst` (caller sized it as sum of lengths).
// Returns 0 on success.
int mxtpu_recordio_read(const char* path, const uint64_t* offsets,
                        const uint64_t* lengths, long long count,
                        uint8_t* dst) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t* p = dst;
  for (long long i = 0; i < count; ++i) {
    if (fseek(f, (long)offsets[i], SEEK_SET) != 0) { fclose(f); return -1; }
    if (fread(p, 1, (size_t)lengths[i], f) != lengths[i]) {
      fclose(f);
      return -1;
    }
    p += lengths[i];
  }
  fclose(f);
  return 0;
}

// HWC uint8 image batch -> CHW float32 with per-channel mean/std
// normalization (the ImageRecordIter inner loop; parity:
// iter_image_recordio_2.cc ProcessImage). n images of h*w*c bytes.
void mxtpu_normalize_hwc_u8_to_chw_f32(const uint8_t* src, float* dst,
                                       long long n, long long h,
                                       long long w, long long c,
                                       const float* mean,
                                       const float* std_inv,
                                       float scale) {
  const long long hw = h * w;
  for (long long i = 0; i < n; ++i) {
    const uint8_t* img = src + i * hw * c;
    float* out = dst + i * hw * c;
    for (long long ch = 0; ch < c; ++ch) {
      const float m = mean ? mean[ch] : 0.0f;
      const float s = std_inv ? std_inv[ch] : 1.0f;
      float* plane = out + ch * hw;
      for (long long p = 0; p < hw; ++p) {
        plane[p] = ((float)img[p * c + ch] * scale - m) * s;
      }
    }
  }
}

// Pack payloads into RecordIO framing in one pass: writes
// magic|lrecord|payload|pad for each record into dst; returns bytes
// written (caller sized dst as sum of 8 + padded lengths).
long long mxtpu_recordio_pack(const uint8_t* payloads,
                              const uint64_t* lengths, long long count,
                              uint8_t* dst) {
  const uint8_t* src = payloads;
  uint8_t* p = dst;
  for (long long i = 0; i < count; ++i) {
    uint32_t len = (uint32_t)lengths[i];
    uint32_t header[2] = {kMagic, len};
    memcpy(p, header, 8);
    p += 8;
    memcpy(p, src, len);
    src += len;
    p += len;
    uint32_t pad = ((len + 3u) & ~3u) - len;
    memset(p, 0, pad);
    p += pad;
  }
  return (long long)(p - dst);
}

}  // extern "C"
