// Native IO runtime: RecordIO scanning + image batch normalization.
//
// Parity role: the reference's C++ data-pipeline hot paths —
// dmlc-core's RecordIOReader (src/io/image_recordio.h framing) and the
// image normalization inner loops of iter_image_recordio_2.cc
// (ImageRecordIOParser2<DType>::ProcessImage). The Python framework
// binds these through ctypes (no pybind11 in the image); everything here
// is plain C ABI.
//
// Build: g++ -O3 -shared -fPIC (see native/build.py; rebuilt on demand,
// cached next to this source).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

static const uint32_t kMagic = 0xced7230a;
static const uint32_t kLRecBits = 29;

// Scan a RecordIO file for magic-framed records. Fills caller-provided
// arrays (capacity `cap`) with each record's payload offset and length.
// Returns the number of records found, or -1 on IO error, or -(needed)
// if cap was too small (caller retries with a larger buffer).
long long mxtpu_recordio_scan(const char* path, uint64_t* offsets,
                              uint64_t* lengths, long long cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  long long n = 0;
  uint32_t header[2];
  for (;;) {
    long pos = ftell(f);
    size_t got = fread(header, sizeof(uint32_t), 2, f);
    if (got != 2) break;  // EOF
    if (header[0] != kMagic) { fclose(f); return -1; }
    uint64_t len = header[1] & ((1u << kLRecBits) - 1);
    uint32_t cflag = header[1] >> kLRecBits;
    if (cflag != 0) {
      // multi-part records: skip continuation framing (rare; the
      // Python path handles them; report as unsupported)
      fclose(f);
      return -1;
    }
    if (n >= cap) { fclose(f); return -(n + 1); }
    offsets[n] = (uint64_t)pos + 2 * sizeof(uint32_t);
    lengths[n] = len;
    ++n;
    uint64_t padded = (len + 3u) & ~3ull;
    if (fseek(f, (long)(pos + 8 + (long)padded), SEEK_SET) != 0) break;
  }
  fclose(f);
  return n;
}

// Read `count` records' payloads (given offsets/lengths from scan) into
// one contiguous buffer `dst` (caller sized it as sum of lengths).
// Returns 0 on success.
int mxtpu_recordio_read(const char* path, const uint64_t* offsets,
                        const uint64_t* lengths, long long count,
                        uint8_t* dst) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t* p = dst;
  for (long long i = 0; i < count; ++i) {
    if (fseek(f, (long)offsets[i], SEEK_SET) != 0) { fclose(f); return -1; }
    if (fread(p, 1, (size_t)lengths[i], f) != lengths[i]) {
      fclose(f);
      return -1;
    }
    p += lengths[i];
  }
  fclose(f);
  return 0;
}

// HWC uint8 image batch -> CHW float32 with per-channel mean/std
// normalization (the ImageRecordIter inner loop; parity:
// iter_image_recordio_2.cc ProcessImage). n images of h*w*c bytes.
void mxtpu_normalize_hwc_u8_to_chw_f32(const uint8_t* src, float* dst,
                                       long long n, long long h,
                                       long long w, long long c,
                                       const float* mean,
                                       const float* std_inv,
                                       float scale) {
  const long long hw = h * w;
  for (long long i = 0; i < n; ++i) {
    const uint8_t* img = src + i * hw * c;
    float* out = dst + i * hw * c;
    for (long long ch = 0; ch < c; ++ch) {
      const float m = mean ? mean[ch] : 0.0f;
      const float s = std_inv ? std_inv[ch] : 1.0f;
      float* plane = out + ch * hw;
      for (long long p = 0; p < hw; ++p) {
        plane[p] = ((float)img[p * c + ch] * scale - m) * s;
      }
    }
  }
}

// Pack payloads into RecordIO framing in one pass: writes
// magic|lrecord|payload|pad for each record into dst; returns bytes
// written (caller sized dst as sum of 8 + padded lengths).
long long mxtpu_recordio_pack(const uint8_t* payloads,
                              const uint64_t* lengths, long long count,
                              uint8_t* dst) {
  const uint8_t* src = payloads;
  uint8_t* p = dst;
  for (long long i = 0; i < count; ++i) {
    uint32_t len = (uint32_t)lengths[i];
    uint32_t header[2] = {kMagic, len};
    memcpy(p, header, 8);
    p += 8;
    memcpy(p, src, len);
    src += len;
    p += len;
    uint32_t pad = ((len + 3u) & ~3u) - len;
    memset(p, 0, pad);
    p += pad;
  }
  return (long long)(p - dst);
}

}  // extern "C"

// ------------------------------------------------------------------------
// JPEG batch decode + bilinear resize (parity: the OMP ParseChunk decode
// loop of iter_image_recordio_2.cc:79,146 — the input-pipeline hot path
// that must outrun the chip's training consumption rate). Uses the
// system libjpeg(-turbo); one OMP thread per image.

#ifndef MXTPU_NO_JPEG
#include <csetjmp>
#include <jpeglib.h>
#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* e = reinterpret_cast<JerrMgr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// decode one JPEG to RGB u8 then bilinear-resize into out (oh*ow*3)
bool decode_resize_one(const uint8_t* buf, uint64_t len, int oh, int ow,
                       uint8_t* out) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  // volatile: modified between setjmp and longjmp — without it the
  // error path would free() an indeterminate register copy (C11
  // 7.13.2.1) under -O3
  uint8_t* volatile pixels = nullptr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(pixels);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int h = cinfo.output_height, w = cinfo.output_width;
  const int stride = w * 3;
  pixels = static_cast<uint8_t*>(malloc(static_cast<size_t>(h) * stride));
  if (!pixels) { jpeg_destroy_decompress(&cinfo); return false; }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pixels + static_cast<size_t>(cinfo.output_scanline) *
                   stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize (h, w) -> (oh, ow)
  const float sy = oh > 1 ? float(h - 1) / float(oh - 1) : 0.f;
  const float sx = ow > 1 ? float(w - 1) / float(ow - 1) : 0.f;
  for (int y = 0; y < oh; ++y) {
    const float fy = y * sy;
    const int y0 = int(fy), y1 = y0 + 1 < h ? y0 + 1 : h - 1;
    const float wy = fy - y0;
    for (int x = 0; x < ow; ++x) {
      const float fx = x * sx;
      const int x0 = int(fx), x1 = x0 + 1 < w ? x0 + 1 : w - 1;
      const float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        const float p00 = pixels[(size_t(y0) * w + x0) * 3 + c];
        const float p01 = pixels[(size_t(y0) * w + x1) * 3 + c];
        const float p10 = pixels[(size_t(y1) * w + x0) * 3 + c];
        const float p11 = pixels[(size_t(y1) * w + x1) * 3 + c];
        const float v = p00 * (1 - wy) * (1 - wx) + p01 * (1 - wy) * wx +
                        p10 * wy * (1 - wx) + p11 * wy * wx;
        out[(size_t(y) * ow + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
  free(pixels);
  return true;
}

// Fused in-loop augmentation: crop + horizontal mirror + per-channel
// multiplicative color jitter from a decoded (dh, dw) RGB image into the
// final (oh, ow) training-ready HWC row. The arithmetic (float32 mul,
// +0.5, truncate, clamp 255) is kept EXACTLY equal to the pure-Python
// fallback in io/io.py (_augment_py), so the two paths are bit-compatible
// given identical decoded pixels.
void augment_into(const uint8_t* src, int dw, int cy, int cx, int oh,
                  int ow, int mirror, const float* jit, uint8_t* out) {
  for (int y = 0; y < oh; ++y) {
    const uint8_t* srow = src + (size_t(cy + y) * dw + cx) * 3;
    uint8_t* drow = out + size_t(y) * ow * 3;
    for (int x = 0; x < ow; ++x) {
      const uint8_t* sp = srow + (mirror ? (ow - 1 - x) : x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float v = float(sp[c]) * jit[c] + 0.5f;
        drow[x * 3 + c] = v >= 255.0f ? 255 : uint8_t(v);
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode `n` JPEGs (payloads at blob+offsets[i], lengths[i]) into an
// (n, oh, ow, 3) u8 HWC buffer, OMP-parallel over images (`n_threads`
// bounds the team; <=0 means the OMP default). Returns the number
// successfully decoded; failed slots are zero-filled and their index
// recorded in `failed` (capacity n, -1 terminated).
long long mxtpu_decode_jpeg_batch(const uint8_t* blob,
                                  const uint64_t* offsets,
                                  const uint64_t* lengths, long long n,
                                  int oh, int ow, uint8_t* out,
                                  long long* failed, int n_threads) {
  long long ok = 0;
  long long nfail = 0;
#ifdef _OPENMP
  // num_threads clause, NOT omp_set_num_threads: the setter is
  // process-global and would throttle every later OMP region
  const int team = n_threads > 0 ? n_threads : omp_get_max_threads();
#pragma omp parallel for schedule(dynamic) reduction(+:ok) num_threads(team)
#endif
  for (long long i = 0; i < n; ++i) {
    uint8_t* dst = out + static_cast<size_t>(i) * oh * ow * 3;
    if (decode_resize_one(blob + offsets[i], lengths[i], oh, ow, dst)) {
      ++ok;
    } else {
      memset(dst, 0, static_cast<size_t>(oh) * ow * 3);
#ifdef _OPENMP
#pragma omp critical
#endif
      { failed[nfail++] = i; }
    }
  }
  if (nfail < n) failed[nfail] = -1;
  return ok;
}

// The streaming-data-plane hot path (parity: the augmenter chain that
// iter_image_recordio_2.cc runs INSIDE its OMP ParseChunk loop): decode
// `n` JPEGs to an oversized (dh, dw) scratch, then crop to (oh, ow) at
// per-image (crop_y[i], crop_x[i]), mirror when mirror[i], and apply the
// per-image per-channel jitter factors jitter[i*3..] — all fused in one
// worker-thread pass producing the training-ready HWC row directly into
// `out` (n, oh, ow, 3). NULL crop/mirror/jitter mean offset 0 / no flip /
// factor 1. Returns the number decoded; failures zero-fill + are listed
// in `failed` (-1 terminated) for the caller's per-record PIL retry.
long long mxtpu_decode_augment_batch(
    const uint8_t* blob, const uint64_t* offsets, const uint64_t* lengths,
    long long n, int dh, int dw, int oh, int ow, const int32_t* crop_y,
    const int32_t* crop_x, const uint8_t* mirror, const float* jitter,
    uint8_t* out, long long* failed, int n_threads) {
  long long ok = 0;
  long long nfail = 0;
  static const float kOnes[3] = {1.0f, 1.0f, 1.0f};
#ifdef _OPENMP
  const int team = n_threads > 0 ? n_threads : omp_get_max_threads();
#pragma omp parallel for schedule(dynamic) reduction(+:ok) num_threads(team)
#endif
  for (long long i = 0; i < n; ++i) {
    uint8_t* dst = out + static_cast<size_t>(i) * oh * ow * 3;
    uint8_t* scratch =
        static_cast<uint8_t*>(malloc(static_cast<size_t>(dh) * dw * 3));
    const bool good = scratch != nullptr &&
        decode_resize_one(blob + offsets[i], lengths[i], dh, dw, scratch);
    if (good) {
      augment_into(scratch, dw, crop_y ? crop_y[i] : 0,
                   crop_x ? crop_x[i] : 0, oh, ow,
                   mirror ? mirror[i] : 0,
                   jitter ? jitter + i * 3 : kOnes, dst);
      ++ok;
    } else {
      memset(dst, 0, static_cast<size_t>(oh) * ow * 3);
#ifdef _OPENMP
#pragma omp critical
#endif
      { failed[nfail++] = i; }
    }
    free(scratch);
  }
  if (nfail < n) failed[nfail] = -1;
  return ok;
}

}  // extern "C"
#endif  // MXTPU_NO_JPEG
