// libmxtpu — the C ABI of the TPU-native framework.
//
// Parity target: src/c_api/c_api.cc in the reference (MX* entry points,
// int status returns, thread-local error buffer). The reference's C layer
// fronts a C++ runtime; this one embeds CPython and trampolines into
// mxnet_tpu.capi_bridge, because the framework's runtime is the Python/JAX
// stack and XLA owns the device code. Every entry point is GIL-safe so the
// library can be driven from any host thread.
//
// Build:
//   g++ -O2 -shared -fPIC -std=c++17 mxtpu_c_api.cc -o libmxtpu.so \
//       $(python3-config --includes) $(python3-config --ldflags --embed)
#include <Python.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
typedef void *NDArrayHandle;
}

namespace {

thread_local std::string tls_error;
thread_local std::vector<int64_t> tls_shape;

std::once_flag g_init_flag;
PyObject *g_bridge = nullptr;      // mxnet_tpu.capi_bridge module
bool g_we_initialized = false;     // we own the interpreter lifecycle

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tls_error = "unknown python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) tls_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// One-time interpreter + bridge import. Returns 0 on success.
int ensure_init() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      g_we_initialized = true;
    }
    PyGILState_STATE gil = PyGILState_Ensure();
    g_bridge = PyImport_ImportModule("mxnet_tpu.capi_bridge");
    if (g_bridge == nullptr) set_error_from_python();
    PyGILState_Release(gil);
    if (g_we_initialized) {
      // release the GIL acquired by Py_Initialize so other threads (and
      // later PyGILState_Ensure calls on this one) can take it
      PyThreadState *ts = PyGILState_GetThisThreadState();
      if (ts != nullptr && PyGILState_Check()) PyEval_SaveThread();
    }
  });
  if (g_bridge == nullptr) {
    if (tls_error.empty()) tls_error = "mxnet_tpu.capi_bridge import failed";
    return -1;
  }
  return 0;
}

// Call bridge.<fn>(*args) with the GIL held; returns new reference or
// nullptr (error already recorded).
PyObject *bridge_call(const char *fn, PyObject *args) {
  PyObject *callable = PyObject_GetAttrString(g_bridge, fn);
  if (callable == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *result = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  Py_XDECREF(args);
  if (result == nullptr) set_error_from_python();
  return result;
}

class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return tls_error.c_str(); }

int MXGetVersion(int *out) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *r = bridge_call("version", PyTuple_New(0));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNotifyShutdown(void) {
  // The embedded interpreter stays alive for the process (finalizing JAX
  // runtimes mid-process is unsafe); parity: MXNotifyShutdown is likewise
  // a sync-and-detach notification, not a teardown.
  if (g_bridge == nullptr) return 0;
  GilGuard gil;
  PyObject *r = bridge_call("waitall", PyTuple_New(0));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayCreate(const int64_t *shape, int ndim, int dtype,
                    NDArrayHandle *out) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject *args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, shp);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(dtype));
  PyObject *r = bridge_call("create", args);
  if (r == nullptr) return -1;
  *out = static_cast<NDArrayHandle>(r);  // owned reference
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, int *out_ndim,
                      const int64_t **out_pdata) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyObject *r = bridge_call("shape", args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(r);
  tls_shape.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    tls_shape[static_cast<size_t>(i)] =
        PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  Py_DECREF(r);
  *out_ndim = static_cast<int>(n);
  *out_pdata = tls_shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyObject *r = bridge_call("dtype_code", args);
  if (r == nullptr) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArraySize(NDArrayHandle handle, int64_t *out_size) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyObject *r = bridge_call("size", args);
  if (r == nullptr) return -1;
  *out_size = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t nbytes) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *buf =
      PyBytes_FromStringAndSize(static_cast<const char *>(data),
                                static_cast<Py_ssize_t>(nbytes));
  PyObject *args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 1, buf);
  PyObject *r = bridge_call("copy_from_bytes", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t nbytes) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyObject *r = bridge_call("to_bytes", args);
  if (r == nullptr) return -1;
  char *src = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &src, &len) != 0) {
    set_error_from_python();
    Py_DECREF(r);
    return -1;
  }
  if (static_cast<size_t>(len) != nbytes) {
    tls_error = "MXNDArraySyncCopyToCPU: byte-size mismatch (have " +
                std::to_string(len) + ", caller asked " +
                std::to_string(nbytes) + ")";
    Py_DECREF(r);
    return -1;
  }
  std::memcpy(data, src, nbytes);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitAll(void) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *r = bridge_call("waitall", PyTuple_New(0));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXListAllOpNames(int *out_size, const char ***out_array) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  // cached for the process lifetime — callers never free. call_once guards
  // the fill: bridge_call may yield the GIL mid-way, so a bare empty()
  // check would let a second thread double-fill and dangle the pointers.
  static std::once_flag fill_flag;
  static std::vector<std::string> storage;
  static std::vector<const char *> pointers;
  static bool fill_ok = false;
  std::call_once(fill_flag, []() {
    PyObject *r = bridge_call("list_ops", PyTuple_New(0));
    if (r == nullptr) return;
    Py_ssize_t n = PyList_Size(r);
    storage.reserve(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *c = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
      storage.emplace_back(c != nullptr ? c : "");
    }
    Py_DECREF(r);
    pointers.reserve(storage.size());
    for (const auto &s : storage) pointers.push_back(s.c_str());
    fill_ok = true;
  });
  if (!fill_ok) {
    if (tls_error.empty()) tls_error = "MXListAllOpNames: op query failed";
    return -1;
  }
  *out_size = static_cast<int>(pointers.size());
  *out_array = pointers.data();
  return 0;
}

int MXImperativeInvoke(const char *op_name, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject *h = static_cast<PyObject *>(inputs[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(ins, i, h);
  }
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject *args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(op_name));
  PyTuple_SET_ITEM(args, 1, ins);
  PyTuple_SET_ITEM(args, 2, keys);
  PyTuple_SET_ITEM(args, 3, vals);
  PyObject *r = bridge_call("invoke", args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  NDArrayHandle *out = static_cast<NDArrayHandle *>(
      std::malloc(sizeof(NDArrayHandle) * static_cast<size_t>(n)));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);  // handle owns a reference
    out[i] = static_cast<NDArrayHandle>(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(n);
  *outputs = out;
  return 0;
}

int MXHandleArrayFree(NDArrayHandle *handles) {
  std::free(handles);
  return 0;
}

}  // extern "C"

// ----------------------------------------------------------- predictor -----
// parity: src/c_api/c_predict_api.cc (MXPredCreate/SetInput/Forward/
// GetOutput/Free) — the standalone inference surface.

extern "C" {

typedef void *PredictorHandle;

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 int num_input_nodes, const char **input_keys,
                 const int64_t *input_shape_indptr,
                 const int64_t *input_shape_data, PredictorHandle *out) {
  (void)dev_type;
  (void)dev_id;
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *names = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (int i = 0; i < num_input_nodes; ++i) {
    PyList_SET_ITEM(names, i, PyUnicode_FromString(input_keys[i]));
    int64_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shp = PyTuple_New(static_cast<Py_ssize_t>(hi - lo));
    for (int64_t j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shp, static_cast<Py_ssize_t>(j - lo),
                       PyLong_FromLongLong(input_shape_data[j]));
    PyList_SET_ITEM(shapes, i, shp);
  }
  PyObject *args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(symbol_json_str));
  PyTuple_SET_ITEM(args, 1,
                   PyBytes_FromStringAndSize(
                       static_cast<const char *>(param_bytes), param_size));
  PyTuple_SET_ITEM(args, 2, names);
  PyTuple_SET_ITEM(args, 3, shapes);
  PyObject *r = bridge_call("pred_create", args);
  if (r == nullptr) return -1;
  *out = static_cast<PredictorHandle>(r);
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const void *data, int64_t nbytes) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 1, PyUnicode_FromString(key));
  PyTuple_SET_ITEM(args, 2,
                   PyBytes_FromStringAndSize(
                       static_cast<const char *>(data),
                       static_cast<Py_ssize_t>(nbytes)));
  PyObject *r = bridge_call("pred_set_input", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyObject *r = bridge_call("pred_forward", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, int index, int *out_ndim,
                         const int64_t **out_pdata) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(index));
  PyObject *r = bridge_call("pred_output_shape", args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(r);
  tls_shape.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    tls_shape[static_cast<size_t>(i)] =
        PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  Py_DECREF(r);
  *out_ndim = static_cast<int>(n);
  *out_pdata = tls_shape.data();
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, int index, void *data,
                    int64_t nbytes) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject *>(handle));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(index));
  PyObject *r = bridge_call("pred_output_bytes", args);
  if (r == nullptr) return -1;
  char *src = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &src, &len) != 0 ||
      len != static_cast<Py_ssize_t>(nbytes)) {
    if (len != static_cast<Py_ssize_t>(nbytes))
      tls_error = "MXPredGetOutput: byte-size mismatch";
    else
      set_error_from_python();
    Py_DECREF(r);
    return -1;
  }
  std::memcpy(data, src, static_cast<size_t>(nbytes));
  Py_DECREF(r);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

}  // extern "C"

// ------------------------------------------------------------ symbol API --

extern "C" {
typedef void *SymbolHandle;
}

namespace {
thread_local std::string tls_json;
thread_local std::vector<std::string> tls_strs;
thread_local std::vector<const char *> tls_str_ptrs;
// MXNDArrayLoad gets its own storage: its names must stay valid until
// the next LOAD (header contract), not until any string-list call
thread_local std::vector<std::string> tls_load_strs;
thread_local std::vector<const char *> tls_load_ptrs;

// bridge fn(handle-or-string) -> string, returned via tls_json
int call_to_string(const char *fn, PyObject *arg, const char **out) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, arg);
  PyObject *r = bridge_call(fn, args);
  if (r == nullptr) return -1;
  const char *c = PyUnicode_AsUTF8(r);
  if (c == nullptr) {
    set_error_from_python();
    Py_DECREF(r);
    return -1;
  }
  tls_json = c;
  Py_DECREF(r);
  *out = tls_json.c_str();
  return 0;
}

// bridge fn(handle) -> list[str], returned via tls string storage
int call_to_strlist(const char *fn, PyObject *arg, int *out_size,
                    const char ***out_array) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, arg);
  PyObject *r = bridge_call(fn, args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  tls_strs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *c = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
    tls_strs.emplace_back(c ? c : "");
  }
  Py_DECREF(r);
  tls_str_ptrs.clear();
  for (const auto &s : tls_strs) tls_str_ptrs.push_back(s.c_str());
  *out_size = static_cast<int>(n);
  *out_array = tls_str_ptrs.data();
  return 0;
}

PyObject *incref_handle(void *h) {
  Py_INCREF(static_cast<PyObject *>(h));
  return static_cast<PyObject *>(h);
}
}  // namespace

extern "C" {

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(json));
  PyObject *r = bridge_call("symbol_from_json", args);
  if (r == nullptr) return -1;
  *out = static_cast<SymbolHandle>(r);
  return 0;
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(fname));
  PyObject *r = bridge_call("symbol_from_file", args);
  if (r == nullptr) return -1;
  *out = static_cast<SymbolHandle>(r);
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json) {
  GilGuard gil;
  return call_to_string("symbol_to_json", incref_handle(handle), out_json);
}

int MXSymbolFree(SymbolHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

int MXSymbolListArguments(SymbolHandle handle, int *out_size,
                          const char ***out_array) {
  GilGuard gil;
  return call_to_strlist("symbol_list_arguments", incref_handle(handle),
                         out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle handle, int *out_size,
                        const char ***out_array) {
  GilGuard gil;
  return call_to_strlist("symbol_list_outputs", incref_handle(handle),
                         out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle handle, int *out_size,
                                const char ***out_array) {
  GilGuard gil;
  return call_to_strlist("symbol_list_aux", incref_handle(handle),
                         out_size, out_array);
}

// Reflected parameter schema of one op as JSON (parity role:
// MXSymbolGetAtomicSymbolInfo's argument listing, fed by ops/schema.py)
int MXSymbolGetAtomicSymbolInfo(const char *op_name, const char **out_json) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  return call_to_string("op_schema_json", PyUnicode_FromString(op_name),
                        out_json);
}

// --------------------------------------------------- ndarray save / load --

int MXNDArraySave(const char *fname, int num_args, NDArrayHandle *handles,
                  const char **keys) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *hs = PyList_New(num_args);
  for (int i = 0; i < num_args; ++i)
    PyList_SET_ITEM(hs, i, incref_handle(handles[i]));
  PyObject *ks;
  if (keys != nullptr) {
    ks = PyList_New(num_args);
    for (int i = 0; i < num_args; ++i)
      PyList_SET_ITEM(ks, i, PyUnicode_FromString(keys[i]));
  } else {
    ks = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject *args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(fname));
  PyTuple_SET_ITEM(args, 1, hs);
  PyTuple_SET_ITEM(args, 2, ks);
  PyObject *r = bridge_call("nd_save", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char *fname, int *out_size,
                  NDArrayHandle **out_handles, int *out_name_size,
                  const char ***out_names) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(fname));
  PyObject *r = bridge_call("nd_load", args);
  if (r == nullptr) return -1;
  PyObject *names = PyTuple_GET_ITEM(r, 0);
  PyObject *arrays = PyTuple_GET_ITEM(r, 1);
  Py_ssize_t n = PyList_Size(arrays);
  auto **handles = static_cast<NDArrayHandle *>(
      malloc(sizeof(NDArrayHandle) * (n + 1)));
  tls_load_strs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    handles[i] = a;
    const char *c = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    tls_load_strs.emplace_back(c ? c : "");
  }
  handles[n] = nullptr;
  tls_load_ptrs.clear();
  for (const auto &s : tls_load_strs) tls_load_ptrs.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<int>(n);
  *out_handles = handles;
  *out_name_size = static_cast<int>(n);
  *out_names = tls_load_ptrs.data();
  return 0;
}

int MXRandomSeed(int seed) {
  if (ensure_init() != 0) return -1;
  GilGuard gil;
  PyObject *args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyLong_FromLong(seed));
  PyObject *r = bridge_call("random_seed", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
