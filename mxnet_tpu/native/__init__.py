"""Native C++ runtime components, bound via ctypes.

The reference implements its data pipeline (RecordIO reader, image
normalization) in C++ (`src/io/`); this package provides the TPU
framework's native equivalents. The shared library builds on demand with
the system toolchain (g++ -O3) and is cached alongside the source; every
entry point has a pure-Python fallback so the framework works without a
compiler.

API:
  recordio_scan(path) -> (offsets, lengths)   # index a .rec without .idx
  recordio_read(path, offsets, lengths) -> list[bytes]
  normalize_batch(u8_hwc, mean, std) -> f32 chw
  decode_jpeg_batch / decode_augment_batch  # OMP decode(+augment) loops
  available() -> bool, status() -> dict      # why the native path is off
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as _np

__all__ = ["available", "status", "recordio_scan", "recordio_read",
           "normalize_batch", "recordio_pack", "decode_jpeg_batch",
           "decode_augment_batch"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "mxtpu_io.cc")
_LIB_PATH = os.path.join(_HERE, "libmxtpu_io.so")
_lib = None
_tried = False
_error = None  # why the probe failed (cached; surfaced ONCE, see _load)


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
           _SRC, "-o", _LIB_PATH, "-ljpeg"]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except subprocess.CalledProcessError:
        # hosts without libjpeg/OpenMP: build without the decode path
        # (decode_jpeg_batch falls back to Python; the rest still works)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-DMXTPU_NO_JPEG", _SRC, "-o", _LIB_PATH]
        subprocess.run(cmd, check=True, capture_output=True)


def _record_failure(exc):
    """Cache WHY the native path is off and surface it exactly once —
    a warning + telemetry counter instead of the old silent per-call
    degradation (every later call sees the cached probe result;
    tools/diagnose.py's "Data Plane" report prints the reason)."""
    global _error
    if isinstance(exc, subprocess.CalledProcessError):
        stderr = (exc.stderr or b"").decode(errors="replace").strip()
        _error = f"build failed (rc {exc.returncode}): {stderr[-400:]}"
    else:
        _error = f"{type(exc).__name__}: {exc}"
    try:
        from .. import log as _log

        _log.get_logger("mxnet_tpu.native").warning(
            "native IO library unavailable (%s); RecordIO/decode fall "
            "back to pure Python — see tools/diagnose.py 'Data Plane'",
            _error)
    except Exception:
        pass
    try:
        from ..telemetry import registry as _registry

        _registry.counter(
            "mxtpu_native_unavailable_total",
            "Native IO library probe/build failures (Python fallback "
            "active)").inc()
    except Exception:
        pass


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_LIB_PATH) or \
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
            _build()
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # a stale checked-in .so linked against libs this host lacks
            # (e.g. libjpeg): rebuild for THIS host — _build() falls back
            # to the no-jpeg variant, preserving every other native path
            _build()
            lib = ctypes.CDLL(_LIB_PATH)
        lib.mxtpu_recordio_scan.restype = ctypes.c_longlong
        lib.mxtpu_recordio_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_longlong]
        lib.mxtpu_recordio_read.restype = ctypes.c_int
        lib.mxtpu_recordio_read.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_uint8)]
        lib.mxtpu_normalize_hwc_u8_to_chw_f32.restype = None
        lib.mxtpu_recordio_pack.restype = ctypes.c_longlong
        if hasattr(lib, "mxtpu_decode_jpeg_batch"):
            lib.mxtpu_decode_jpeg_batch.restype = ctypes.c_longlong
            lib.mxtpu_decode_jpeg_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_longlong,
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        if hasattr(lib, "mxtpu_decode_augment_batch"):
            lib.mxtpu_decode_augment_batch.restype = ctypes.c_longlong
            lib.mxtpu_decode_augment_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_longlong,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        _lib = lib
    except Exception as e:
        _lib = None
        _record_failure(e)
    return _lib


def available():
    """True when the native library is built and loadable."""
    return _load() is not None


def status():
    """The data-plane probe result, for tools/diagnose.py and tests:
    availability of the lib and of each optional capability, plus the
    cached failure reason when the native path is off."""
    lib = _load()
    return {
        "available": lib is not None,
        "lib_path": _LIB_PATH,
        "built": os.path.exists(_LIB_PATH),
        "jpeg": bool(lib is not None
                     and hasattr(lib, "mxtpu_decode_jpeg_batch")),
        "augment": bool(lib is not None
                        and hasattr(lib, "mxtpu_decode_augment_batch")),
        "error": _error,
    }


def recordio_scan(path):
    """Index a .rec file: returns (offsets, lengths) numpy arrays of each
    record's payload. Native scan when available, else a Python walk."""
    lib = _load()
    if lib is not None:
        cap = 1024
        while True:
            offs = _np.zeros(cap, _np.uint64)
            lens = _np.zeros(cap, _np.uint64)
            n = lib.mxtpu_recordio_scan(
                path.encode(), offs.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint64)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                cap)
            if n >= 0:
                return offs[:n].copy(), lens[:n].copy()
            if n == -1:
                break  # IO/framing error: fall back to Python
            cap = -int(n) * 2
    return _py_scan(path)


def _py_scan(path):
    import struct

    offsets, lengths = [], []
    with open(path, "rb") as f:
        while True:
            pos = f.tell()
            head = f.read(8)
            if len(head) < 8:
                break
            magic, lrec = struct.unpack("<II", head)
            if magic != 0xCED7230A:
                raise ValueError(f"bad RecordIO magic at {pos}")
            if lrec >> 29:
                raise ValueError(
                    "multi-part RecordIO records (cflag != 0) are not "
                    "supported by the scanner; use the sequential reader")
            length = lrec & ((1 << 29) - 1)
            offsets.append(pos + 8)
            lengths.append(length)
            f.seek((length + 3) // 4 * 4, os.SEEK_CUR)
    return (_np.asarray(offsets, _np.uint64),
            _np.asarray(lengths, _np.uint64))


def recordio_read(path, offsets, lengths):
    """Read the payloads for (offsets, lengths); returns list[bytes]."""
    offsets = _np.ascontiguousarray(offsets, _np.uint64)
    lengths = _np.ascontiguousarray(lengths, _np.uint64)
    lib = _load()
    total = int(lengths.sum())
    if lib is not None:
        buf = _np.zeros(total, _np.uint8)
        rc = lib.mxtpu_recordio_read(
            path.encode(),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(offsets),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc == 0:
            out, p = [], 0
            for ln in lengths:
                out.append(buf[p:p + int(ln)].tobytes())
                p += int(ln)
            return out
    out = []
    with open(path, "rb") as f:
        for off, ln in zip(offsets, lengths):
            f.seek(int(off))
            out.append(f.read(int(ln)))
    return out


def normalize_batch(images_u8_hwc, mean=None, std=None, scale=1.0):
    """(N, H, W, C) uint8 -> (N, C, H, W) float32 with channel mean/std
    (the ImageRecordIter inner loop, native when available)."""
    images_u8_hwc = _np.ascontiguousarray(images_u8_hwc, _np.uint8)
    n, h, w, c = images_u8_hwc.shape
    lib = _load()
    if lib is not None:
        out = _np.empty((n, c, h, w), _np.float32)
        mean_arr = (_np.ascontiguousarray(mean, _np.float32)
                    if mean is not None else None)
        std_inv = (1.0 / _np.ascontiguousarray(std, _np.float32)
                   if std is not None else None)
        fptr = ctypes.POINTER(ctypes.c_float)
        lib.mxtpu_normalize_hwc_u8_to_chw_f32(
            images_u8_hwc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(fptr),
            ctypes.c_longlong(n), ctypes.c_longlong(h),
            ctypes.c_longlong(w), ctypes.c_longlong(c),
            mean_arr.ctypes.data_as(fptr) if mean_arr is not None
            else None,
            std_inv.ctypes.data_as(fptr) if std_inv is not None else None,
            ctypes.c_float(scale))
        return out
    out = images_u8_hwc.astype(_np.float32) * scale
    if mean is not None:
        out = out - _np.asarray(mean, _np.float32)
    if std is not None:
        out = out / _np.asarray(std, _np.float32)
    return out.transpose(0, 3, 1, 2).copy()


def recordio_pack(payloads):
    """Frame a list of payload bytes into RecordIO wire format; returns
    one bytes object (native single pass when available)."""
    lengths = _np.asarray([len(p) for p in payloads], _np.uint64)
    lib = _load()
    if lib is not None:
        src = _np.frombuffer(b"".join(payloads), _np.uint8)
        total = int(sum(8 + (int(l) + 3) // 4 * 4 for l in lengths))
        dst = _np.zeros(total, _np.uint8)
        n = lib.mxtpu_recordio_pack(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(payloads),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        return dst[:n].tobytes()
    import struct

    out = bytearray()
    for p in payloads:
        out += struct.pack("<II", 0xCED7230A, len(p))
        out += p
        out += b"\x00" * ((len(p) + 3) // 4 * 4 - len(p))
    return bytes(out)


def _blob_offsets(bufs):
    """Concatenate payloads + per-record (offsets, lengths) for the OMP
    decode entry points."""
    n = len(bufs)
    offsets = _np.zeros(n, _np.uint64)
    lengths = _np.zeros(n, _np.uint64)
    pos = 0
    for i, b in enumerate(bufs):
        offsets[i] = pos
        lengths[i] = len(b)
        pos += len(b)
    blob = _np.frombuffer(b"".join(bufs), _np.uint8)
    return blob, offsets, lengths


def decode_augment_batch(bufs, dh, dw, oh, ow, crop_y=None, crop_x=None,
                         mirror=None, jitter=None, n_threads=0):
    """Fused decode + augmentation (the streaming-data-plane hot path):
    decode each JPEG to (dh, dw), crop to (oh, ow) at per-image
    (crop_y[i], crop_x[i]), mirror where mirror[i], scale channels by
    jitter[i] — one pass per worker thread, producing training-ready
    HWC rows with no intermediate Python copy (parity: the augmenter
    chain inside iter_image_recordio_2.cc's OMP ParseChunk loop).
    Returns (batch, failed_idx) like :func:`decode_jpeg_batch`, or None
    when the native path is unavailable (caller falls back to the
    bit-compatible Python augmenter)."""
    lib = _load()
    if lib is None or not hasattr(lib, "mxtpu_decode_augment_batch"):
        return None
    n = len(bufs)
    blob, offsets, lengths = _blob_offsets(bufs)
    out = _np.empty((n, oh, ow, 3), _np.uint8)
    failed = _np.full(n, -1, _np.int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    cy = (_np.ascontiguousarray(crop_y, _np.int32)
          if crop_y is not None else None)
    cx = (_np.ascontiguousarray(crop_x, _np.int32)
          if crop_x is not None else None)
    mir = (_np.ascontiguousarray(mirror, _np.uint8)
           if mirror is not None else None)
    jit = (_np.ascontiguousarray(jitter, _np.float32)
           if jitter is not None else None)
    lib.mxtpu_decode_augment_batch(
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, dh, dw, oh, ow,
        cy.ctypes.data_as(i32p) if cy is not None else None,
        cx.ctypes.data_as(i32p) if cx is not None else None,
        mir.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if mir is not None else None,
        jit.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        if jit is not None else None,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        int(n_threads))
    bad = [int(i) for i in failed if i >= 0]
    return out, bad


def decode_jpeg_batch(bufs, out_h, out_w, n_threads=0):
    """Decode a list of JPEG byte strings into an (N, out_h, out_w, 3)
    uint8 HWC array, resized bilinearly, OMP-parallel in C++ (parity:
    iter_image_recordio_2.cc ParseChunk). `n_threads` bounds the OMP
    team (0 = OMP default). Returns (batch, failed_idx list); None when
    the native decode path is unavailable (caller falls back to PIL)."""
    lib = _load()
    if lib is None or not hasattr(lib, "mxtpu_decode_jpeg_batch"):
        return None
    n = len(bufs)
    blob_arr, offsets, lengths = _blob_offsets(bufs)
    out = _np.empty((n, out_h, out_w, 3), _np.uint8)
    failed = _np.full(n, -1, _np.int64)
    lib.mxtpu_decode_jpeg_batch(
        blob_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, out_h, out_w,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        int(n_threads))
    bad = [int(i) for i in failed if i >= 0]
    return out, bad
