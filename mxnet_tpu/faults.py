"""Deterministic fault-injection harness + retry/backoff utilities.

Production training on TPU pods dies to preemptions, corrupt writes, flaky
data sources and NaN'd steps. The recovery paths (CheckpointManager resume,
retry loops, NaN step guards, deferred-exception surfacing) are only real if
they can be *exercised*; this module provides named injection points wired
through the stack:

    ``io.decode``      per-record image decode (io/io.py ImageRecordIter)
    ``kvstore.push``   gradient aggregation (kvstore/kvstore.py push)
    ``engine.flush``   bulk-segment flush / wait_all sync points (engine.py,
                       bulk.py) — errors surface AT the sync point, per the
                       engine's deferred-exception contract
    ``trainer.step``   the compiled train step (parallel/sharded_trainer.py)
    ``ckpt.write``     checkpoint file writes (checkpoint.py)
    ``compile.load``   persistent compile-cache reads (compile.py) — the
                       entry bytes are the payload, so ``corrupt`` mode
                       exercises the CRC-mismatch recompile fallback
    ``compile.write``  persistent compile-cache writes (compile.py)
    ``serving.batch``  every in-flight serving batch (serving/batcher.py)
                       — ``hang`` is the wedged-device drill the serving
                       watchdog deadline converts into a crash bundle +
                       failed batch (server keeps serving), ``preempt``
                       the SIGTERM-mid-load drain drill
    ``serving.route``  every fleet-router dispatch, BEFORE a candidate
                       worker is picked (serving/fleet.py) — ``delay``
                       slows a route (straggler/hedge-threshold drills),
                       ``raise`` surfaces as a router 500 to the client
                       without touching any worker
    ``modelbus.publish``  every bus record publish (modelbus.py), fired
                       AFTER the finite gate — ``nan`` poisons the
                       record's first parameter (in-transit corruption
                       the SUBSCRIBER must reject + quarantine; the
                       poison-rejection drill of chaos phase 14),
                       ``delay``/``hang`` stall the publisher
    ``modelbus.apply``  every subscriber apply attempt, on the raw
                       payload bytes — ``corrupt`` flips bytes the CRC
                       validation must catch (reject: crc_mismatch),
                       ``delay``/``hang`` stall the watcher, ``raise``
                       rejects as apply_error
    ``cluster.observe``  the reconcile loop's observation half
                       (cluster.py ClusterSupervisor), fired inside the
                       ``cluster.observe`` watchdog span — ``hang``/
                       ``delay`` wedge the pass so the watchdog ladder
                       fires like any other stalled sync point
    ``cluster.act``    every reconcile action before it is performed
                       (spawn/drain/restart/scale/gc); the action dict
                       is the payload — ``raise`` aborts one action,
                       ``hang`` wedges the act half under its span
    ``supervisor.act`` alias span fired alongside ``cluster.act`` —
                       the chaos phase 16 crash drill arms it to down
                       the supervisor mid-action and prove the
                       restarted one re-adopts from the world record

Faults are configured programmatically (:func:`configure`) or through the
``MXNET_TPU_FAULTS`` environment variable — read once, at first use, so
subprocess tests can inherit a schedule. The schedule is deterministic and
seedable: every point counts its own invocations, and probabilistic
triggers draw from a dedicated ``random.Random(seed)`` stream, never the
global RNG.

Spec grammar (semicolon-separated entries)::

    <point>:<mode>[@<trigger>][:<arg>]

    mode     raise | delay | corrupt | nan | kill | hang | preempt
             | peerloss
    trigger  N        fire on the N-th invocation only (1-based)
             N+       fire on every invocation from the N-th onward
             N,M,...  fire on the listed invocations
             *        fire on every invocation
             pP       fire with probability P per invocation (seeded)
             (default: 1 — fire on the first invocation)
    arg      delay: sleep seconds (default 0.05)
             hang: wedge seconds (default 3600 — "forever" at test scale)
             peerloss: the gang rank to SIGKILL (required)
             raise/corrupt/nan/kill: unused

Examples::

    MXNET_TPU_FAULTS="ckpt.write:raise@2"          # 2nd write fails
    MXNET_TPU_FAULTS="io.decode:delay@*:0.01"      # every decode +10ms
    MXNET_TPU_FAULTS="trainer.step:nan@3+"         # NaN grads from step 3
    MXNET_TPU_FAULTS="trainer.step:kill@5"         # SIGKILL on 5th step
    MXNET_TPU_FAULTS="trainer.step:preempt@6"      # SIGTERM on 6th step
    MXNET_TPU_FAULTS="trainer.step:peerloss@6:1"   # SIGKILL gang rank 1

Modes at a point ``faults.point(name, payload=None)``:

    raise    raise :class:`InjectedFault`
    delay    time.sleep(arg seconds), then continue
    corrupt  payload is bytes-like -> flipped bytes are RETURNED (callers
             that pass payloads must use the return value); other payloads
             fall back to ``nan``
    nan      payload is a numpy/jax array -> a NaN-poisoned copy is
             returned (callers use the return value)
    kill     SIGKILL the process — the "hard-preempted mid-step" scenario
             for kill-and-resume tests (no atexit, no cleanup, exactly
             like a TPU preemption whose grace window has expired)
    preempt  deliver SIGTERM to the process and CONTINUE — the *planned*
             preemption (30s-grace SIGTERM). With the mxnet_tpu.preempt
             handlers installed the in-flight step finishes and the run
             drains gracefully; without them the process dies like a real
             unhandled SIGTERM — both paths deterministically testable
    hang     block the calling thread for `arg` seconds (default 3600) —
             the "stuck collective / wedged fetch" scenario the watchdog
             (mxnet_tpu.watchdog) exists to detect; every watchdog path
             is deterministically testable with it
    peerloss SIGKILL the gang peer holding rank `arg` (pid looked up
             through its heartbeat file in MXTPU_GANG_DIR, see
             mxnet_tpu.elastic.kill_peer) and CONTINUE — the "a peer
             host just vanished" scenario the elastic gang supervisor
             exists to recover from, seedable and deterministic like
             every other fault; naming the *own* rank is a self-SIGKILL

:func:`retry` is the reusable exponential-backoff wrapper used by the io
decode path and the model-zoo fetch path; injected faults are retryable
like any other exception, so retry loops are testable under the harness.
"""
from __future__ import annotations

import functools
import os
import random as _pyrandom
import threading
import time

__all__ = ["InjectedFault", "configure", "reset", "point", "active",
           "stats", "retry"]


class InjectedFault(RuntimeError):
    """Raised by an injection point whose schedule fired (mode=raise)."""


class _PointSpec:
    __slots__ = ("mode", "trigger", "arg", "rng")

    def __init__(self, mode, trigger, arg, seed):
        self.mode = mode
        self.trigger = trigger  # ("set", {n,..}) | ("from", n) | ("p", prob)
        self.arg = arg
        # dedicated stream: deterministic regardless of global RNG use
        self.rng = _pyrandom.Random(seed)

    def fires(self, count):
        kind, val = self.trigger
        if kind == "set":
            return count in val
        if kind == "from":
            return count >= val
        return self.rng.random() < val  # "p"


_lock = threading.Lock()
_specs = {}   # point name -> _PointSpec
_counts = {}  # point name -> invocation count
_fired = {}   # point name -> fire count
_loaded_env = False


def _parse_trigger(tok):
    if tok == "*":
        return ("from", 1)
    if tok.startswith("p"):
        return ("p", float(tok[1:]))
    if tok.endswith("+"):
        return ("from", int(tok[:-1]))
    return ("set", {int(t) for t in tok.split(",")})


def _parse(spec, seed):
    """Parse a spec string into {point: _PointSpec}."""
    out = {}
    for i, entry in enumerate(e for e in spec.split(";") if e.strip()):
        parts = entry.strip().split(":")
        if len(parts) < 2:
            raise ValueError(
                f"bad MXNET_TPU_FAULTS entry {entry!r}: expected "
                "<point>:<mode>[@<trigger>][:<arg>]")
        name, mode_tok = parts[0], parts[1]
        arg = parts[2] if len(parts) > 2 else None
        if "@" in mode_tok:
            mode, trig_tok = mode_tok.split("@", 1)
        else:
            mode, trig_tok = mode_tok, "1"
        if mode not in ("raise", "delay", "corrupt", "nan", "kill", "hang",
                        "preempt", "peerloss"):
            raise ValueError(f"unknown fault mode {mode!r} in {entry!r}")
        # per-point sub-seed keeps streams independent yet reproducible
        out[name] = _PointSpec(mode, _parse_trigger(trig_tok),
                               arg, seed + i * 7919)
    return out


def configure(spec=None, seed=0):
    """Install a fault schedule (replacing any previous one).

    spec : str in the grammar above, or dict {point: spec-entry-tail}
        e.g. ``{"ckpt.write": "raise@2"}``, or None to clear.
    seed : int — seeds the probabilistic triggers deterministically.
    """
    global _loaded_env
    if isinstance(spec, dict):
        spec = ";".join(f"{k}:{v}" for k, v in spec.items())
    with _lock:
        _specs.clear()
        _counts.clear()
        _fired.clear()
        if spec:
            _specs.update(_parse(spec, seed))
        _loaded_env = True  # explicit configure overrides the env


def reset():
    """Clear the schedule and all counters (env var will NOT be re-read)."""
    configure(None)


def _ensure_env():
    global _loaded_env
    if _loaded_env:
        return
    with _lock:
        if _loaded_env:
            return
        env = os.environ.get("MXNET_TPU_FAULTS", "")
        if env:
            _specs.update(_parse(env, int(os.environ.get(
                "MXNET_TPU_FAULTS_SEED", "0"))))
        _loaded_env = True


def active() -> bool:
    """True when any injection point is armed (fast gate for hot paths)."""
    _ensure_env()
    return bool(_specs)


def stats():
    """{point: (invocations, fires)} for every point that has been hit."""
    with _lock:
        return {k: (_counts.get(k, 0), _fired.get(k, 0))
                for k in set(_counts) | set(_fired)}


def _corrupt_bytes(payload, rng):
    b = bytearray(payload)
    if not b:
        return bytes(b)
    for _ in range(max(1, len(b) // 64)):
        i = rng.randrange(len(b))
        b[i] ^= 0xFF
    return bytes(b)


def _poison_nan(payload):
    import numpy as _np

    arr = _np.array(_np.asarray(payload), copy=True)
    if arr.dtype.kind != "f":
        arr = arr.astype(_np.float32)
    flat = arr.reshape(-1)
    flat[: max(1, flat.size // 8)] = _np.nan
    return arr


def point(name, payload=None):
    """Hit the named injection point.

    Returns `payload` (possibly corrupted — callers that pass payloads must
    use the return value), raises :class:`InjectedFault`, sleeps, or kills
    the process, per the armed schedule. With no schedule armed this is a
    counter increment and a dict miss — cheap enough for per-batch paths.
    """
    _ensure_env()
    if not _specs:
        return payload
    with _lock:
        count = _counts.get(name, 0) + 1
        _counts[name] = count
        spec = _specs.get(name)
        if spec is None or not spec.fires(count):
            return payload
        _fired[name] = _fired.get(name, 0) + 1
    if spec.mode == "raise":
        raise InjectedFault(f"injected fault at {name!r} "
                            f"(invocation {count})")
    if spec.mode == "delay":
        time.sleep(float(spec.arg) if spec.arg else 0.05)
        return payload
    if spec.mode == "hang":
        # chunked so signals (per-test SIGALRM) still interrupt promptly
        end = time.monotonic() + (float(spec.arg) if spec.arg else 3600.0)
        while True:
            remaining = end - time.monotonic()
            if remaining <= 0:
                return payload
            time.sleep(min(0.25, remaining))
    if spec.mode == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)  # no return
    if spec.mode == "preempt":
        import signal

        # SIGTERM to self: with preempt.install()'ed handlers this only
        # raises the drain flag (execution continues and the step
        # finishes); without them the interpreter dies like a real
        # unhandled preemption
        os.kill(os.getpid(), signal.SIGTERM)
        return payload
    if spec.mode == "peerloss":
        from . import elastic as _elastic

        # SIGKILL a named gang peer and continue — this process then
        # observes the loss the real way (PeerLostError / supervisor)
        _elastic.kill_peer(int(spec.arg) if spec.arg is not None
                           else None)
        return payload
    if spec.mode == "corrupt" and isinstance(payload, (bytes, bytearray)):
        return _corrupt_bytes(payload, spec.rng)
    if payload is not None:  # corrupt (non-bytes) and nan both poison
        return _poison_nan(payload)
    raise InjectedFault(f"injected fault at {name!r} (mode "
                        f"{spec.mode!r} with no payload to corrupt)")


# ----------------------------------------------------------------- retry ---

def retry(fn=None, *, retries=3, backoff=0.05, jitter=0.0, deadline=None,
          retry_on=(Exception,), on_retry=None):
    """Exponential-backoff retry decorator/wrapper.

    Replaces ad-hoc retry loops (io decode PIL fallback, model-zoo fetch).
    Usable three ways::

        @retry                                   # defaults
        @retry(retries=5, retry_on=(OSError,))   # configured decorator
        retry(fn, retries=5)(args...)            # inline wrapper

    retries : attempts AFTER the first call (total calls = retries + 1).
    backoff : initial sleep; doubles each retry (exponential).
    jitter  : fraction of the sleep drawn uniformly at random and added
        (0.0 = fully deterministic — the default, so tests and seeded
        chaos runs replay exactly).
    deadline : total-elapsed-time cap in seconds across ALL attempts and
        backoff sleeps; once starting the next backoff would cross it the
        last exception propagates instead. Bounds retry storms so a
        persistently failing call cannot itself become a hang (the
        attempt-count cap alone grows exponentially in wall-clock).
    retry_on : exception classes that trigger a retry; anything else
        propagates immediately.
    on_retry : optional callback ``(attempt, exc)`` per failed attempt
        (logging / profiler hooks).
    """
    if fn is not None and not callable(fn):
        raise TypeError("retry: first argument must be callable; use "
                        "keyword arguments for configuration")

    def deco(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            delay = backoff
            start = time.monotonic()
            for attempt in range(retries + 1):
                try:
                    return func(*args, **kwargs)
                except retry_on as exc:
                    if attempt == retries:
                        raise
                    sleep = delay
                    if jitter:
                        sleep += delay * jitter * _pyrandom.random()
                    if deadline is not None and \
                            time.monotonic() - start + sleep >= deadline:
                        raise  # the next attempt would bust the time cap
                    if on_retry is not None:
                        on_retry(attempt + 1, exc)
                    if sleep > 0:
                        time.sleep(sleep)
                    delay *= 2

        return wrapper

    return deco(fn) if fn is not None else deco
