"""Profiler: chrome-trace dump + aggregate stats.

Parity target: `src/profiler/profiler.h:251-299` (chrome-trace JSON dump,
`Profiler::DumpProfile`), `src/profiler/aggregate_stats.cc` (console table)
and the Python surface `python/mxnet/profiler.py:32-150` (`set_config`,
`set_state`, `pause`/`resume`, `dump`, `dumps`) plus the instrumentation
objects (`Domain`, `Task`, `Frame`, `Event`, `Counter`, `Marker`).

TPU-native: host-side op dispatch events are recorded by the imperative
dispatch path (`ndarray._invoke`) and CachedOp executions; device-side
traces come from XLA via ``jax.profiler`` when ``profile_device=True`` is
passed to :func:`set_config` (written next to the chrome trace as
``<filename>.device/`` in TensorBoard format — the XLA analogue of the
reference's per-stream GPU events). The chrome trace loads directly in
``chrome://tracing`` / Perfetto.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dump",
           "dumps", "reset", "trace_info", "Domain", "Task", "Frame",
           "Event", "Counter", "Marker", "scope", "record_skip_step",
           "record_stall", "record_cache", "record_compile",
           "record_serving"]

_lock = threading.Lock()
_RECORDING = False       # master flag: a session is active and not paused
_REC_IMPERATIVE = False  # fast-path flag read by ndarray._invoke
_REC_SYMBOLIC = False    # fast-path flag read by CachedOp
_session = False         # between set_state('run') and set_state('stop')
_paused = False
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "continuous_dump": False,
    "dump_period": 1.0,
    "profile_device": False,
    "profile_process": "worker",
}
_events = []  # chrome trace events
_aggregate = {}  # name -> [count, total_us, min_us, max_us]
_epoch = time.perf_counter()
_epoch_mono = time.monotonic()  # same instant: the cross-clock anchor
_device_trace_active = False


def _now_us():
    return (time.perf_counter() - _epoch) * 1e6


def _refresh():
    """Recompute the per-category fast-path flags."""
    global _REC_IMPERATIVE, _REC_SYMBOLIC
    _REC_IMPERATIVE = _RECORDING and _config["profile_imperative"]
    _REC_SYMBOLIC = _RECORDING and _config["profile_symbolic"]


def set_config(**kwargs):
    """Configure the profiler (parity: profiler.py:32 set_config)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError(f"unknown profiler config keys: {sorted(unknown)}")
    _config.update(kwargs)
    if _config.get("profile_all"):
        for k in ("profile_symbolic", "profile_imperative", "profile_memory",
                  "profile_api", "aggregate_stats"):
            _config[k] = True
    _refresh()


def set_state(state="stop", profile_process="worker"):
    """Start ('run') or stop ('stop') profiling (parity: set_state)."""
    global _RECORDING, _paused, _session, _device_trace_active
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state == "run":
        if not _session:
            _session = True
            if _config["profile_device"]:
                try:
                    import jax

                    jax.profiler.start_trace(_config["filename"] + ".device")
                    _device_trace_active = True
                except Exception:
                    _device_trace_active = False
        _RECORDING = True
        _paused = False
    else:
        if _session and _device_trace_active:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            _device_trace_active = False
        _session = False
        _RECORDING = False
        _paused = False
    _refresh()


def state():
    return "run" if _RECORDING else "stop"


def pause(profile_process="worker"):
    """Temporarily stop recording without ending the session."""
    global _RECORDING, _paused
    if _session and _RECORDING:
        _RECORDING = False
        _paused = True
        _refresh()


def resume(profile_process="worker"):
    global _RECORDING, _paused
    if _session and _paused:
        _RECORDING = True
        _paused = False
        _refresh()


def reset():
    """Drop all recorded events and aggregate stats."""
    with _lock:
        _events.clear()
        _aggregate.clear()


def record_event(name, start_us, dur_us, cat="operator", tid=None,
                 args=None):
    """Append one complete ('X') chrome-trace event + aggregate stats.

    The hot-path entry used by ndarray._invoke / CachedOp (parity:
    profiler.h:251 ProfileOperator events on the engine workers)."""
    ev = {"name": name, "cat": cat, "ph": "X", "pid": os.getpid(),
          "tid": tid if tid is not None else threading.get_ident(),
          "ts": start_us, "dur": dur_us}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        agg = _aggregate.get(name)
        if agg is None:
            _aggregate[name] = [1, dur_us, dur_us, dur_us]
        else:
            agg[0] += 1
            agg[1] += dur_us
            agg[2] = min(agg[2], dur_us)
            agg[3] = max(agg[3], dur_us)


def record_bulk_segment(start_us, dur_us, op_names):
    """One complete event per flushed bulk segment (engine bulking,
    mxnet_tpu.bulk): op count + fused op list ride in args so traces show
    what each fused XLA executable contains — the observability the
    reference loses when ops merge into one engine job is kept here."""
    record_event(f"BulkSegment[{len(op_names)}]", start_us, dur_us,
                 cat="bulk",
                 args={"op_count": len(op_names),
                       "ops": ",".join(op_names)})


def record_skip_step(total, consecutive):
    """NaN/Inf-guarded optimizer step skipped (ShardedTrainer nan_guard):
    an instant marker at the skip plus a counter track of the running
    total, so diverging runs are visible in the trace. No-op unless a
    profiling session is recording."""
    if not _RECORDING:
        return
    record_instant("trainer.skip_step", cat="trainer",
                   args={"total": total, "consecutive": consecutive})
    record_counter("trainer.skipped_steps", total)


_stall_count = 0


def record_stall(point, elapsed_s, bundle):
    """Watchdog stall: an instrumented point blew its deadline and a crash
    bundle was written (mxnet_tpu.watchdog). Recorded as an instant marker
    plus a running counter track so hangs line up with the op timeline in
    the trace. No-op unless a profiling session is recording."""
    global _stall_count
    _stall_count += 1
    if not _RECORDING:
        return
    record_instant("watchdog.stall", cat="watchdog",
                   args={"point": point, "elapsed_s": round(elapsed_s, 3),
                         "bundle": bundle})
    record_counter("watchdog.stalls", _stall_count)


def record_cache(kind, hits, misses):
    """Dispatch/compile cache-hit/miss counter tracks (fed by
    ``analysis.distcheck.cache_event`` — per-op jit dispatch, bulk
    fused-segment, and CachedOp signature caches). Two counter tracks per
    cache family so hit ratio and recompile churn line up with the op
    timeline in the trace. No-op unless a session is recording (the
    caller checks ``_RECORDING`` first to stay off the dispatch hot
    path)."""
    record_counter(f"compile_cache.{kind}.hits", hits)
    record_counter(f"compile_cache.{kind}.misses", misses)


def record_compile(site, dur_ms, source, hits, misses):
    """One compile-service miss resolution (mxnet_tpu.compile): a complete
    event spanning the compile/disk-load ('compile' | 'disk' | 'warmup')
    plus the per-site hit/miss counter tracks, all under the existing
    ``compile_cache.*`` family so service traffic lines up with the
    dispatch/bulk/cachedop cache tracks in the trace. No-op unless a
    profiling session is recording."""
    if not _RECORDING:
        return
    now = _now_us()
    record_event(f"compile[{site}]", now - dur_ms * 1e3, dur_ms * 1e3,
                 cat="compile", args={"source": source})
    record_cache(f"service.{site}", hits, misses)


def record_serving(model, bucket, rows, dur_ms, queue_depth):
    """One served batch (mxnet_tpu.serving): a complete event spanning
    the compiled bucket execution plus queue-depth / batch-rows counter
    tracks, so serving latency and backlog line up with the compile-cache
    and dispatch tracks in the trace. No-op unless a profiling session is
    recording."""
    if not _RECORDING:
        return
    now = _now_us()
    record_event(f"serving[{model}]", now - dur_ms * 1e3, dur_ms * 1e3,
                 cat="serving",
                 args={"bucket": bucket, "rows": rows})
    record_counter(f"serving.{model}.queue_depth", queue_depth)
    record_counter(f"serving.{model}.batch_rows", rows)


def record_instant(name, cat="instant", args=None):
    # dur: 0 — instants/counters are durationless in the chrome-trace
    # model, but downstream consumers (and the subsystem tests) treat
    # ts/dur/ph as the universal event envelope; viewers ignore it
    ev = {"name": name, "cat": cat, "ph": "i", "pid": os.getpid(),
          "tid": threading.get_ident(), "ts": _now_us(), "dur": 0,
          "s": "p"}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def record_counter(name, value):
    with _lock:
        _events.append({"name": name, "cat": "counter", "ph": "C",
                        "pid": os.getpid(), "tid": 0, "ts": _now_us(),
                        "dur": 0, "args": {name: value}})


def trace_info():
    """The recorded chrome events plus the monotonic instant matching
    the profiler's perf_counter epoch — so ``telemetry.trace.dump()``
    can re-base profiler events onto the span/flight timeline (both
    clocks are CLOCK_MONOTONIC-backed on the platforms we run on)."""
    with _lock:
        return {"epoch_mono": _epoch_mono, "events": list(_events)}


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace JSON to `filename` (parity: MXDumpProfile /
    Profiler::DumpProfile, profiler.h:266)."""
    with _lock:
        payload = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(payload, f)
    if finished:
        set_state("stop")


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Return aggregate statistics as a console table (parity:
    MXAggregateProfileStatsPrint, aggregate_stats.cc)."""
    with _lock:
        rows = [(name, c, tot / 1e3, mn / 1e3, mx / 1e3, tot / c / 1e3)
                for name, (c, tot, mn, mx) in _aggregate.items()]
    key = {"total": 2, "count": 1, "min": 3, "max": 4, "avg": 5,
           "name": 0}[sort_by]
    rows.sort(key=lambda r: r[key], reverse=not ascending)
    lines = ["Profile Statistics:",
             f"{'Name':<40s} {'Count':>8s} {'Total(ms)':>12s} "
             f"{'Min(ms)':>10s} {'Max(ms)':>10s} {'Avg(ms)':>10s}"]
    for name, c, tot, mn, mx, avg in rows:
        lines.append(f"{name[:40]:<40s} {c:>8d} {tot:>12.3f} {mn:>10.3f} "
                     f"{mx:>10.3f} {avg:>10.3f}")
    if reset:
        globals()["reset"]()
    return "\n".join(lines)


# ------------------------------------------------- instrumentation objects --

class Domain:
    """Named profiling domain (parity: profiler.py Domain)."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        c = Counter(self, name)
        if value is not None:
            c.set_value(value)
        return c

    def new_marker(self, name):
        return Marker(self, name)

    def __str__(self):
        return self.name


class _Span:
    """start()/stop() span recorded as one complete event."""

    _cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is None:
            return
        if _RECORDING:
            record_event(self.name, self._start, _now_us() - self._start,
                         cat=f"{self.domain}:{self._cat}")
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def __str__(self):
        return self.name


class Task(_Span):
    _cat = "task"


class Frame(_Span):
    _cat = "frame"


class Event(_Span):
    """Standalone event (no domain; parity: profiler.py Event)."""

    _cat = "event"

    def __init__(self, name):
        super().__init__("event", name)


class Counter:
    """Monotonic counter rendered as a chrome counter track."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if _RECORDING:
            record_counter(self.name, value)

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    """Instant marker (parity: profiler.py Marker.mark)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if _RECORDING:
            record_instant(self.name, cat=f"{self.domain}:marker")


class scope:
    """Context manager tagging ops with a name scope (used by tests and
    gluon name scopes; minimal parity with profiler scope in the
    reference's imperative API)."""

    _current = ""

    def __init__(self, name):
        self.name = name
        self._prev = None

    def __enter__(self):
        self._prev = scope._current
        scope._current = self.name
        return self

    def __exit__(self, *exc):
        scope._current = self._prev
