"""Foundation helpers: dtype registry, error types, name managers.

Role parity: `python/mxnet/base.py` in the reference (ctypes lib loading,
dtype maps, MXNetError). Here the "backend" is JAX/XLA, so this module only
keeps the pure-Python pieces: dtype canonicalisation, error types, and small
utilities shared across the package.
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: dmlc error -> MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype universe. bf16 is first-class on TPU (MXU native input type);
# fp64 is supported on CPU meshes for numeric-gradient tests.
_DTYPE_ALIASES = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "uint8": "uint8",
    "int8": "int8",
    "int32": "int32",
    "int64": "int64",
    "bool": "bool",
}


def canonical_dtype(dtype):
    """Normalise a dtype-ish value to a numpy/ml_dtypes dtype object."""
    import jax.numpy as jnp

    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.bfloat16
        if dtype not in _DTYPE_ALIASES:
            raise TypeError(f"unsupported dtype {dtype!r}")
        return _np.dtype(dtype)
    if dtype is jnp.bfloat16:
        return jnp.bfloat16
    try:
        d = _np.dtype(dtype)
    except TypeError:
        # jax weak types / ml_dtypes
        return dtype
    return d


def dtype_name(dtype) -> str:
    import jax.numpy as jnp

    if dtype is jnp.bfloat16:
        return "bfloat16"
    return _np.dtype(dtype).name if not hasattr(dtype, "name") else str(getattr(dtype, "name"))


class _NameManager(threading.local):
    """Automatic unique-name generation (parity: mxnet.name.NameManager)."""

    def __init__(self):
        super().__init__()
        self.counters = {}

    def get(self, hint: str) -> str:
        idx = self.counters.get(hint, 0)
        self.counters[hint] = idx + 1
        return f"{hint}{idx}"


name_manager = _NameManager()
