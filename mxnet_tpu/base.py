"""Foundation helpers: dtype registry, error types, name managers.

Role parity: `python/mxnet/base.py` in the reference (ctypes lib loading,
dtype maps, MXNetError). Here the "backend" is JAX/XLA, so this module only
keeps the pure-Python pieces: dtype canonicalisation, error types, and small
utilities shared across the package.
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types",
           "did_you_mean"]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: dmlc error -> MXNetError)."""


def did_you_mean(name, candidates, n=1):
    """A ``" (did you mean ...?)"`` suffix for a near-miss name, or ``""``.

    The one difflib helper shared by every naming-error site — OpSchema
    kwargs, the operator registry, DeviceMesh axis names, and the distcheck
    sharding verifier — so all of them hint the same way."""
    import difflib

    close = difflib.get_close_matches(str(name),
                                      [str(c) for c in candidates], n=n)
    if not close:
        return ""
    if len(close) == 1:
        return f" (did you mean {close[0]!r}?)"
    return f" (did you mean one of {close}?)"


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype universe. bf16 is first-class on TPU (MXU native input type);
# fp64 is supported on CPU meshes for numeric-gradient tests.
_DTYPE_ALIASES = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "uint8": "uint8",
    "int8": "int8",
    "int32": "int32",
    "int64": "int64",
    "bool": "bool",
}


def canonical_dtype(dtype):
    """Normalise a dtype-ish value to a numpy/ml_dtypes dtype object."""
    import jax.numpy as jnp

    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.bfloat16
        if dtype not in _DTYPE_ALIASES:
            raise TypeError(f"unsupported dtype {dtype!r}")
        return _np.dtype(dtype)
    if dtype is jnp.bfloat16:
        return jnp.bfloat16
    try:
        d = _np.dtype(dtype)
    except TypeError:
        # jax weak types / ml_dtypes
        return dtype
    return d


def dtype_name(dtype) -> str:
    import jax.numpy as jnp

    if dtype is jnp.bfloat16:
        return "bfloat16"
    return _np.dtype(dtype).name if not hasattr(dtype, "name") else str(getattr(dtype, "name"))


class _NameManager(threading.local):
    """Automatic unique-name generation (parity: mxnet.name.NameManager)."""

    def __init__(self):
        super().__init__()
        self.counters = {}

    def get(self, hint: str) -> str:
        idx = self.counters.get(hint, 0)
        self.counters[hint] = idx + 1
        return f"{hint}{idx}"


name_manager = _NameManager()


def apply_platform_env():
    """Honor MXTPU_PLATFORM=cpu|tpu at import time. Environments that
    pre-import jax with a pinned platform (sitecustomize) ignore a later
    JAX_PLATFORMS env var, but jax.config.update still wins as long as no
    backend has been initialised — this is the only portable hook worker
    processes (tools/launch.py children, embedded C hosts) have."""
    import os

    plat = os.environ.get("MXTPU_PLATFORM")
    if not plat:
        return
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception:
        pass  # backend already initialised — keep its platform


def maybe_enable_latency_hiding():
    """Arm XLA's latency-hiding scheduler on non-CPU backends — it
    reorders compiled programs so collectives (the reduce-scatter /
    all-gather pairs the grad-overlap path emits) run concurrently with
    compute instead of serializing after backward.

    ``XLA_FLAGS`` is read once at backend spin-up, so this must run
    before any backend touch (``mxnet_tpu/__init__`` calls it next to
    the platform pin). Applied only when the target platform is
    *known* to be tpu/gpu from the env (an ``--xla_tpu_*`` flag is an
    unknown-flag error on other backends); a user-provided
    latency-hiding setting in ``XLA_FLAGS`` always wins.
    ``MXNET_TPU_LHS=0`` opts out. Returns True when a flag was (or
    already is) in effect."""
    import os

    if os.environ.get("MXNET_TPU_LHS", "1") == "0":
        return False
    plat = (os.environ.get("MXTPU_PLATFORM")
            or os.environ.get("JAX_PLATFORMS", ""))
    plat = plat.split(",")[0].strip().lower()
    flag = {
        "tpu": "--xla_tpu_enable_latency_hiding_scheduler=true",
        "gpu": "--xla_gpu_enable_latency_hiding_scheduler=true",
        "cuda": "--xla_gpu_enable_latency_hiding_scheduler=true",
        "rocm": "--xla_gpu_enable_latency_hiding_scheduler=true",
    }.get(plat)
    if flag is None:
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    if "latency_hiding_scheduler" in flags:
        return True  # the user already decided
    os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    return True


def ensure_live_backend(timeout_s=90, retries=1, reprobe=False):
    """Probe the default JAX backend in a subprocess under a deadline,
    pinning the CPU platform if (and only if) the probe HANGS.

    A downed TPU tunnel makes the first ``jax.devices()`` call block
    forever with no exception to catch, which would hang any entry point
    (bench.py, examples, launch.py children). Returns the platform the
    process will use: the value of an explicit ``MXTPU_PLATFORM`` pin,
    ``"default"`` when the probe succeeds, or ``"cpu-fallback"`` after a
    timeout-triggered fallback (distinct from a deliberate pin, so
    callers can warn honestly). A probe that *crashes* (nonzero exit) is
    retried and then raised as RuntimeError — that is evidence of a
    different, possibly transient, problem (busy device lock, bad env),
    and silently measuring the wrong platform would be worse than
    failing loudly. Must run before anything touches the XLA backend in
    this process; if the fallback cannot be applied because a backend is
    already live, raises instead of claiming success.

    ``reprobe=True`` un-latches an inherited fallback: a pin that an
    EARLIER timeout exported (``MXTPU_PLATFORM_FALLBACK`` marks it —
    a deliberate user pin is always honoured) is re-tested against the
    default backend, so the first run after the tunnel comes back up
    records real-device numbers with no env surgery (bench.py passes
    it on every run)."""
    import os
    import subprocess
    import sys

    pinned = os.environ.get("MXTPU_PLATFORM")
    if pinned and reprobe and os.environ.get("MXTPU_PLATFORM_FALLBACK"):
        env = {k: v for k, v in os.environ.items()
               if k not in ("MXTPU_PLATFORM", "MXTPU_PROBE_OK")}
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True, env=env)
        except subprocess.TimeoutExpired:
            return pinned  # still down; keep the latched fallback
        if proc.returncode != 0:
            return pinned
        # the default backend is reachable again: release the latch for
        # this process (config pin, if we can — nothing has touched the
        # backend yet on the entry-point path) and for every child
        try:
            import jax

            jax.config.update("jax_platforms", None)
        except Exception:
            return pinned  # a backend is already live here; stay honest
        os.environ.pop("MXTPU_PLATFORM", None)
        os.environ.pop("MXTPU_PLATFORM_FALLBACK", None)
        os.environ["MXTPU_PROBE_OK"] = "1"
        return "default"
    if pinned:
        return pinned
    if os.environ.get("MXTPU_PROBE_OK"):
        # a probe already succeeded in this process tree; the backend
        # spin-up is expensive, don't pay for it twice
        return "default"
    last_err = None
    for _ in range(retries + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True)
            if proc.returncode == 0:
                os.environ["MXTPU_PROBE_OK"] = "1"
                return "default"
            last_err = proc.stderr.decode(errors="replace")[-500:]
        except subprocess.TimeoutExpired:
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception as exc:
                raise RuntimeError(
                    "default JAX backend is unreachable (probe timed "
                    "out) and the CPU fallback could not be applied — a "
                    "backend is already initialised in this process; "
                    "call ensure_live_backend before any backend touch"
                ) from exc
            # only after the fallback is actually in effect: make it
            # visible to child processes too — MARKED as a fallback (not
            # a deliberate pin), so a later reprobe=True run may release
            # it once the tunnel is back
            os.environ["MXTPU_PLATFORM"] = "cpu"
            os.environ["MXTPU_PLATFORM_FALLBACK"] = "1"
            return "cpu-fallback"
    raise RuntimeError(
        f"JAX backend probe failed (crash, not a hang):\n{last_err}")


def probe_backend_or_fallback(skip_env="MXTPU_SKIP_PROBE", reprobe=False):
    """Entry-point guard for examples/benchmarks: run the liveness probe
    (unless `skip_env` is set or MXTPU_PLATFORM pins a platform) and
    log a loud warning when a downed tunnel forced the CPU fallback.
    Returns ensure_live_backend's platform string, or "skipped". Call it
    in main() AFTER argument parsing and BEFORE the first backend
    touch. ``reprobe=True`` additionally re-tests a fallback-latched
    CPU pin from an earlier run (never a deliberate user pin), so each
    run gets a fresh shot at the real device."""
    import os

    # MXTPU_SKIP_PROBE always works; callers may add their own knob too
    # (bench.py keeps BENCH_SKIP_PROBE for compatibility)
    if os.environ.get(skip_env) or os.environ.get("MXTPU_SKIP_PROBE"):
        return "skipped"
    plat = ensure_live_backend(reprobe=reprobe)
    from . import log as _log

    if plat == "cpu-fallback":
        _log.get_logger("mxnet_tpu.base").warning(
            "default backend unreachable; running on CPU")
    elif plat == "default" and reprobe:
        _log.get_logger("mxnet_tpu.base").info(
            "default backend reachable; any stale CPU-fallback latch "
            "released")
    return plat


# the gang generation this process last rendezvoused at (None = never):
# an elastic supervisor restart hands workers a NEW generation + a NEW
# coordinator address, and re-joining requires leaving the old epoch
_dist_generation = None


def maybe_init_distributed(generation=None):
    """Join the multi-host rendezvous when launched by tools/launch.py
    (parity: KVStoreDist workers connecting to the dmlc tracker via
    DMLC_* env). jax.distributed.initialize only works BEFORE the XLA
    backend spins up, so mxnet_tpu/__init__ calls this at import; the
    kvstore path calls it again as a fallback and warns loudly instead of
    silently degrading to a single-worker group.

    Coordinator re-rendezvous (elastic gang restarts): a supervisor spawns
    generation N+1 with a fresh ``MXTPU_GANG_GENERATION`` and a fresh
    coordinator port, with surviving ranks renumbered densely. A process
    already joined at an older generation (possible when a surviving
    worker re-enters in place rather than being re-exec'd) leaves the dead
    epoch via ``jax.distributed.shutdown()`` and joins the new one."""
    import logging
    import os

    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return
    num = int(os.environ.get("MXTPU_NUM_WORKERS", "1"))
    if num <= 1:
        return
    if generation is None:
        try:
            generation = int(os.environ.get("MXTPU_GANG_GENERATION", "0"))
        except ValueError:
            generation = 0
    global _dist_generation
    import jax
    from jax._src import distributed as _dist

    log = logging.getLogger("mxnet_tpu")
    if getattr(_dist.global_state, "client", None) is not None:
        if not generation or generation == _dist_generation:
            return  # already joined this incarnation
        # gang restart: the old coordinator epoch is dead — leave it
        # before rendezvousing at the new address
        try:
            jax.distributed.shutdown()
        except Exception as e:
            log.error(
                "gang generation %s -> %s: jax.distributed.shutdown "
                "failed (%s) — this worker cannot re-rendezvous and "
                "stays in its stale group", _dist_generation, generation,
                e)
            return
        log.warning("gang: re-rendezvous at generation %s (coordinator "
                    "%s, %d workers)", generation, coord, num)
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=num,
            process_id=int(os.environ.get("MXTPU_WORKER_ID", "0")))
        _dist_generation = generation or None
    except RuntimeError as e:
        log.error(
            "MXTPU_COORDINATOR=%s is set but jax.distributed could not "
            "initialize (%s) — this worker will run as an ISOLATED "
            "single-process group and dist_* stores will NOT aggregate. "
            "Import mxnet_tpu before running any computation.", coord, e)
