"""Flash attention — blocked online-softmax attention (registry family
``flash_attention``).

Migrated verbatim from ``ops/pallas_ops.py`` (PR 8); that module is now
the op-registration shim calling :func:`mxnet_tpu.kernels.dispatch`.
Forward runs the Pallas kernel (VMEM-blocked, MXU matmuls per tile, the
(S, S) score matrix never materializes in HBM); backward is the blocked
flash recurrence in pure JAX (custom_vjp recomputing probabilities
tile-by-tile), so training memory stays O(S*block) end to end.

Tolerance vs the XLA baseline (dense softmax reference): f32 inputs
agree to rtol=2e-5/atol=2e-5 — the kernel accumulates in f32 exactly
like the reference but reassociates the softmax normalizer across k
blocks, so parity is close-but-not-bitwise (tests/test_pallas.py and
tests/test_kernels.py assert these bounds).
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention_reference", "flash_forward"]


def flash_attention_reference(q, k, v, scale, causal):
    """Dense attention oracle (and the XLA dispatch baseline)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale, causal, block_q, block_k, n_kb):
    """One (batch*head, q-block, k-block) program. The TPU grid iterates
    its LAST dimension sequentially, so the online-softmax state (m, l,
    acc) carries across k blocks in VMEM scratch — only (block, d) tiles
    ever live in VMEM, whatever the sequence length (the FlashAttention
    recurrence)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k_blk = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m = m_ref[...]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # blocks entirely above the diagonal contribute nothing
        @pl.when(ki * block_k < (qi + 1) * block_q)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def flash_forward(q, k, v, scale, causal, block_q, block_k,
                  interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    n_kb = sk // block_k
    grid = (bh, sq // block_q, n_kb)
    kernel = _functools.partial(_flash_kernel, scale=scale, causal=causal,
                                block_q=block_q, block_k=block_k,
                                n_kb=n_kb)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


def _causal_mask(s, qi, ci, bq, bk):
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ci * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, -jnp.inf)


def _flash_backward(q, k, v, out, cot, scale, causal, bq, bk):
    """Blocked flash backward (FlashAttention eq. 13-16) in pure JAX:
    probabilities are recomputed per (q-block, k-block) tile, so live
    memory stays O(S * block) — no (S, S) tensor ever exists, matching
    the forward kernel's memory contract for training too."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nbq, nbk = sq // bq, sk // bk
    f32 = jnp.float32

    def per_head(q2, k2, v2, o2, do2):
        qb = q2.reshape(nbq, bq, d).astype(f32)
        kb = k2.reshape(nbk, bk, d).astype(f32)
        vb = v2.reshape(nbk, bk, d).astype(f32)
        dob = do2.reshape(nbq, bq, d).astype(f32)
        Dvec = (do2.astype(f32) * o2.astype(f32)).sum(-1).reshape(nbq, bq)

        # pass 1: per-row max and normalizer (scan over k blocks)
        def ml_one(qi, qblk):
            def step(carry, kc):
                m, l = carry
                kcblk, ci = kc
                s = qblk @ kcblk.T * scale
                if causal:
                    s = _causal_mask(s, qi, ci, bq, bk)
                m_new = jnp.maximum(m, s.max(-1))
                l = l * jnp.exp(m - m_new) + \
                    jnp.exp(s - m_new[:, None]).sum(-1)
                return (m_new, l), None

            init = (jnp.full((bq,), -jnp.inf, f32), jnp.zeros((bq,), f32))
            (m, l), _ = jax.lax.scan(step, init,
                                     (kb, jnp.arange(nbk)))
            return m, jnp.maximum(l, 1e-30)

        m, l = jax.vmap(ml_one)(jnp.arange(nbq), qb)

        # dq: per q block, accumulate over k blocks
        def dq_one(qi, qblk, doblk, mrow, lrow, Drow):
            def step(acc, kc):
                kcblk, vcblk, ci = kc
                s = qblk @ kcblk.T * scale
                if causal:
                    s = _causal_mask(s, qi, ci, bq, bk)
                p = jnp.exp(s - mrow[:, None]) / lrow[:, None]
                dp = doblk @ vcblk.T
                ds = p * (dp - Drow[:, None])
                return acc + ds @ kcblk * scale, None

            acc, _ = jax.lax.scan(step, jnp.zeros((bq, d), f32),
                                  (kb, vb, jnp.arange(nbk)))
            return acc

        dq = jax.vmap(dq_one)(jnp.arange(nbq), qb, dob, m, l, Dvec)

        # dk, dv: per k block, accumulate over q blocks
        def dkv_one(ci, kcblk, vcblk):
            def step(carry, qc):
                dk_acc, dv_acc = carry
                qblk, doblk, mrow, lrow, Drow, qi = qc
                s = qblk @ kcblk.T * scale
                if causal:
                    s = _causal_mask(s, qi, ci, bq, bk)
                p = jnp.exp(s - mrow[:, None]) / lrow[:, None]
                dp = doblk @ vcblk.T
                ds = p * (dp - Drow[:, None])
                return (dk_acc + ds.T @ qblk * scale,
                        dv_acc + p.T @ doblk), None

            init = (jnp.zeros((bk, d), f32), jnp.zeros((bk, d), f32))
            (dk_acc, dv_acc), _ = jax.lax.scan(
                step, init, (qb, dob, m, l, Dvec, jnp.arange(nbq)))
            return dk_acc, dv_acc

        dk, dv = jax.vmap(dkv_one)(jnp.arange(nbk), kb, vb)
        return (dq.reshape(sq, d), dk.reshape(sk, d), dv.reshape(sk, d))

    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    dq, dk, dv = jax.vmap(per_head)(flat(q), flat(k), flat(v), flat(out),
                                    flat(cot))
    return (dq.reshape(q.shape).astype(q.dtype),
            dk.reshape(k.shape).astype(k.dtype),
            dv.reshape(v.shape).astype(v.dtype))


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    return flash_forward(q, k, v, scale, causal, block_q, block_k,
                         interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = flash_forward(q, k, v, scale, causal, block_q, block_k,
                        interpret)
    return out, (q, k, v, out)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, cot):
    q, k, v, out = res
    return _flash_backward(q, k, v, out, cot, scale, causal, block_q,
                           block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---- registry wiring -------------------------------------------------

def _kernel(q, k, v, scale, causal=False, block_q=128, block_k=128,
            interpret=False):
    return _flash(q, k, v, float(scale), bool(causal), int(block_q),
                  int(block_k), bool(interpret))


def _xla(q, k, v, scale, causal=False, block_q=128, block_k=128):
    del block_q, block_k  # dense path has no blocking
    return flash_attention_reference(q, k, v, scale, causal)


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


def _bucket(q, k, v, scale, causal=False, block_q=128, block_k=128):
    """Sequence lengths and batch*heads round UP to powers of two (one
    table row covers the whole bucket); head dim and dtype are exact —
    they change the kernel's tiling, not just its trip count."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    return (f"bh{_pow2(b * h)}_sq{_pow2(sq)}_sk{_pow2(sk)}_d{d}_"
            f"{jnp.dtype(q.dtype).name}_c{int(bool(causal))}_"
            f"q{block_q}k{block_k}")


def _supports(q, k, v, scale, causal=False, block_q=128, block_k=128):
    """The statically checkable Mosaic constraints: S divisible by the
    block sizes, D a multiple of 8 up to 512, rank-4 input."""
    if q.ndim != 4:
        return False
    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    return (sq % block_q == 0 and sk % block_k == 0
            and d % 8 == 0 and 0 < d <= 512)


def _register():
    from . import register_kernel

    register_kernel(
        "flash_attention", kernel=_kernel, xla=_xla, bucket=_bucket,
        supports=_supports, default_tpu=True,
        tolerance="f32 rtol=2e-5 atol=2e-5 vs dense softmax (softmax "
                  "normalizer reassociated across k blocks)")


_register()
