"""2-bit gradient compression — registry families ``twobit_compress``
and ``twobit_decompress``.

PR 13's kvstore gradient compression runs as unfused XLA soup: the
error-feedback add, two threshold compares, the int8 select and the
residual subtract each stream the gradient through HBM. The compress
kernel does the whole pipeline — ``g = grad + residual``, threshold-
quantize to codes {-1, 0, +1}, write the new residual — in ONE pass
over (rows, 128) tiles; decompress is the matching fused scale-cast of
the (summed) code tensor back to gradient dtype.

Contracts (mirroring ``kvstore/kvstore.py`` bitwise):

  compress:   (grad f32, residual f32, threshold) -> (codes int8,
              new_residual f32) with codes = sign(g) where |g| >= thr
  decompress: (codes intN, threshold) -> codes.astype(f32) * thr
              (the all-reduced code SUM decompresses the same way, so
              values outside {-1,0,+1} are in-contract)

Tolerance vs the XLA baseline: BIT-EXACT for f32 gradients — identical
compare/select/multiply sequence; tests assert ``==``.
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

_LANES = 128
_BLOCK_ROWS = 256


def _pad_rows(n):
    rows = -(-n // _LANES)
    return -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS


def _to_tiles(x):
    flat = x.reshape(-1)
    rows = _pad_rows(flat.size)
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES)


def _from_tiles(t, shape, size):
    return t.reshape(-1)[:size].reshape(shape)


def _compress_body(g_ref, r_ref, codes_ref, res_ref, *, thr):
    g = g_ref[...] + r_ref[...]
    one = jnp.int8(1)
    codes = jnp.where(g >= thr, one,
                      jnp.where(g <= -thr, -one, jnp.int8(0)))
    codes_ref[...] = codes
    res_ref[...] = g - codes.astype(g.dtype) * thr


def _decompress_body(c_ref, o_ref, *, thr):
    o_ref[...] = c_ref[...].astype(o_ref.dtype) * thr


def _kernel_compress(grad, residual, thr, interpret=False):
    from jax.experimental import pallas as pl

    shape, size = grad.shape, grad.size
    g = _to_tiles(grad)
    r = _to_tiles(residual)
    rows = g.shape[0]
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    codes, res = pl.pallas_call(
        _functools.partial(_compress_body, thr=float(thr)),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[blk, blk],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.int8),
                   jax.ShapeDtypeStruct((rows, _LANES), grad.dtype)],
        interpret=interpret,
    )(g, r)
    return (_from_tiles(codes, shape, size),
            _from_tiles(res, shape, size))


def _xla_compress(grad, residual, thr):
    """PR 13 kvstore._quantize math verbatim."""
    g = grad + residual
    one = jnp.int8(1)
    codes = jnp.where(g >= thr, one,
                      jnp.where(g <= -thr, -one, jnp.int8(0)))
    return codes, g - codes.astype(g.dtype) * thr


def _kernel_decompress(codes, thr, dtype=jnp.float32, interpret=False):
    from jax.experimental import pallas as pl

    shape, size = codes.shape, codes.size
    c = _to_tiles(codes)
    rows = c.shape[0]
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _functools.partial(_decompress_body, thr=float(thr)),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.dtype(dtype)),
        interpret=interpret,
    )(c)
    return _from_tiles(out, shape, size)


def _xla_decompress(codes, thr, dtype=jnp.float32):
    return codes.astype(jnp.dtype(dtype)) * thr


def _size_bucket(x):
    n = x.size if hasattr(x, "size") else 1
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _bucket_compress(grad, residual, thr):
    return f"n{_size_bucket(grad)}_{jnp.dtype(grad.dtype).name}"


def _bucket_decompress(codes, thr, dtype=jnp.float32):
    return f"n{_size_bucket(codes)}_{jnp.dtype(dtype).name}"


def _supports_compress(grad, residual, thr):
    return (jnp.dtype(grad.dtype) == jnp.dtype(jnp.float32)
            and grad.shape == residual.shape and grad.size > 0)


def _supports_decompress(codes, thr, dtype=jnp.float32):
    return codes.size > 0


def _register():
    from . import register_kernel

    register_kernel(
        "twobit_compress", kernel=_kernel_compress, xla=_xla_compress,
        bucket=_bucket_compress, supports=_supports_compress,
        tolerance="bit-exact vs kvstore._quantize (same compare/select/"
                  "multiply order)")
    register_kernel(
        "twobit_decompress", kernel=_kernel_decompress,
        xla=_xla_decompress, bucket=_bucket_decompress,
        supports=_supports_decompress,
        tolerance="bit-exact (single f32 multiply)")


_register()
