"""Persisted kernel-dispatch table.

The autotuner (``benchmark/opperf.py --kernels``) times each registered
kernel against its XLA baseline per (backend, family, shape bucket) and
records the winner here; :func:`mxnet_tpu.kernels.dispatch` consults the
table at trace time. Persistence follows the compile-cache discipline
exactly (``mxnet_tpu/compile.py`` disk layer): entries live under
``MXNET_TPU_CACHE_DIR/kernels/dispatch_<fingerprint>.json`` where the
fingerprint folds in jax/jaxlib versions, backend platform, device kind
and count — a backend change makes old measurements invisible instead of
silently mis-routing. Writes are tmp + fsync + rename (concurrent-writer
safe); the payload carries its own CRC32, and a corrupt or mismatched
file loads as EMPTY (dispatch then falls back to the untuned default,
counted by ``mxtpu_kernels_table_corrupt_total``) — a torn write can
never wedge dispatch.

Table format (version 1)::

    {"version": 1, "fingerprint": "<12 hex>", "backend": "cpu|tpu|...",
     "created": <epoch>, "opperf": {...last autotune run stamp...},
     "crc32": <crc of the canonical entries json>,
     "entries": {"<family>|<bucket>": {"winner": "kernel"|"xla",
                                       "kernel_ms": ..., "xla_ms": ...,
                                       "speedup": ..., "interpret": bool}}}

Bucket keys are produced by each registry entry's bucketing function —
a pure function of the aval shapes, so the same workload always lands on
the same row (distcheck pass 4 sweeps the dispatch keys for churn).
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib

__all__ = ["table_path", "load", "save", "lookup", "record", "entries",
           "census", "invalidate", "set_opperf_stamp", "opperf_stamp"]

_lock = threading.RLock()
_loaded = None        # in-memory table dict, or None before first load
_loaded_path = None   # path it came from (staleness check for diagnose)
_corrupt_seen = None  # last corruption reason (diagnose)


def _canon_entries(entries):
    return json.dumps(entries, sort_keys=True, separators=(",", ":"))


def _crc(entries):
    return zlib.crc32(_canon_entries(entries).encode()) & 0xFFFFFFFF


def table_path():
    """The active on-disk table path, or None when no cache dir is
    configured (memory-only dispatch table)."""
    from .. import compile as _compile

    root = _compile.cache_dir()
    if root is None:
        return None
    return os.path.join(root, "kernels",
                        f"dispatch_{_compile.fingerprint()}.json")


def _fresh():
    from .. import compile as _compile

    try:
        import jax

        backend = jax.devices()[0].platform
    except Exception:
        backend = "unknown"
    return {"version": 1, "fingerprint": _compile.fingerprint(),
            "backend": backend, "created": time.time(), "opperf": None,
            "entries": {}}


def _note_corrupt(reason):
    global _corrupt_seen
    _corrupt_seen = reason
    try:
        from ..telemetry import registry as _registry

        _registry.counter(
            "mxtpu_kernels_table_corrupt_total",
            "Kernel dispatch-table files that failed CRC/format "
            "verification and were ignored (dispatch fell back to the "
            "untuned defaults)").inc()
    except Exception:
        pass
    try:
        from .. import log as _log

        _log.get_logger("mxnet_tpu.kernels").warning(
            "kernel dispatch table unreadable (%s); dispatch uses the "
            "untuned per-family defaults until opperf --kernels rewrites "
            "it", reason)
    except Exception:
        pass


def load(reload=False):
    """The live table dict (loaded once per process; ``reload=True``
    re-reads disk — tests and the autotuner use it). Corrupt/stale files
    load as a fresh empty table, never raise."""
    global _loaded, _loaded_path
    with _lock:
        path = table_path()
        if _loaded is not None and not reload and path == _loaded_path:
            return _loaded
        table = _fresh()
        if path is not None and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    raw = json.load(f)
                if raw.get("version") != 1:
                    _note_corrupt(f"unsupported version {raw.get('version')!r}")
                elif raw.get("fingerprint") != table["fingerprint"]:
                    # stale: measured on a different backend/jax — ignore
                    _note_corrupt(
                        f"fingerprint {raw.get('fingerprint')!r} != current "
                        f"{table['fingerprint']!r} (backend/jax changed)")
                elif _crc(raw.get("entries", {})) != raw.get("crc32"):
                    _note_corrupt("entries CRC mismatch (torn write?)")
                else:
                    table = raw
            except (OSError, ValueError) as e:
                _note_corrupt(f"{type(e).__name__}: {e}")
        _loaded = table
        _loaded_path = path
        return table


def save(table=None):
    """Atomically persist the table (tmp + fsync + rename, CRC stamped).
    Returns the path written, or None when no cache dir is configured."""
    with _lock:
        table = table if table is not None else load()
        path = table_path()
        if path is None:
            return None
        table["crc32"] = _crc(table.get("entries", {}))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        data = json.dumps(table, indent=1, sort_keys=True).encode()
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        return path


def _key(family, bucket):
    return f"{family}|{bucket}"


def lookup(family, bucket):
    """The tuned row for (family, bucket) — ``{"winner": ...}`` — or
    None when untuned."""
    return load().get("entries", {}).get(_key(family, bucket))


def record(family, bucket, winner, kernel_ms=None, xla_ms=None,
           interpret=False):
    """Record one autotune measurement (in memory; call :func:`save` to
    persist)."""
    with _lock:
        table = load()
        row = {"winner": winner, "interpret": bool(interpret)}
        if kernel_ms is not None:
            row["kernel_ms"] = round(float(kernel_ms), 5)
        if xla_ms is not None:
            row["xla_ms"] = round(float(xla_ms), 5)
        if kernel_ms and xla_ms:
            row["speedup"] = round(xla_ms / kernel_ms, 3)
        table.setdefault("entries", {})[_key(family, bucket)] = row
        return row


def set_opperf_stamp(stamp):
    """Stamp the last ``opperf --kernels`` run (argv, duration, counts)
    into the table — surfaced by tools/diagnose.py."""
    with _lock:
        load()["opperf"] = stamp


def opperf_stamp():
    return load().get("opperf")


def entries():
    return dict(load().get("entries", {}))


def invalidate():
    """Drop the in-memory table so the next lookup re-reads disk (tests,
    and ``compile.configure`` callers that move the cache dir)."""
    global _loaded, _loaded_path
    with _lock:
        _loaded = None
        _loaded_path = None


def census():
    """Table census for tools/diagnose.py: location, entry/winner counts,
    staleness, last corruption reason, last opperf run."""
    with _lock:
        table = load()
        ents = table.get("entries", {})
        winners = {"kernel": 0, "xla": 0}
        per_family = {}
        for key, row in ents.items():
            fam = key.split("|", 1)[0]
            w = row.get("winner", "xla")
            winners[w] = winners.get(w, 0) + 1
            f = per_family.setdefault(fam, {"kernel": 0, "xla": 0})
            f[w] = f.get(w, 0) + 1
        path = table_path()
        return {
            "path": path,
            "exists": bool(path and os.path.exists(path)),
            "fingerprint": table.get("fingerprint"),
            "backend": table.get("backend"),
            "created": table.get("created"),
            "entries": len(ents),
            "winners": winners,
            "per_family": per_family,
            "corrupt_seen": _corrupt_seen,
            "opperf": table.get("opperf"),
        }
