"""Pallas kernel layer: registry + autotuned per-shape dispatch.

The reference earns its throughput from ~198k LoC of hand-fused CUDA
under ``src/operator/*.cu``; the TPU-native analogue is a *small* set of
Pallas kernels behind a **measured** dispatch seam. Each op family
registers here with

  * a Pallas implementation (``kernel``) — runs natively on TPU, in the
    Pallas interpreter on CPU (numerics test-assertable everywhere);
  * the XLA baseline callable (``xla``) — always correct, always
    available, and the fallback whenever the kernel is untuned,
    unavailable or disabled;
  * a shape-bucketing function (``bucket``) — pure function of the
    input avals, keying the persisted dispatch table;
  * a static-constraint predicate (``supports``) — the Mosaic
    alignment rules the kernel needs, checked before dispatch.

``dispatch(family, *arrays, **kw)`` consults the dispatch table that
``benchmark/opperf.py --kernels`` measured and persisted (same
tmp+fsync+rename/CRC discipline and backend fingerprint as the compile
cache, under ``MXNET_TPU_CACHE_DIR/kernels/`` — :mod:`.table`), so a
kernel only ever runs where it is *measurably* faster; an untuned bucket
takes the family's conservative default (kernel on TPU only for families
proven there, XLA otherwise). ``MXNET_TPU_KERNELS=0`` disables every
kernel — the end-to-end numerics-parity opt-out.

Families shipped (docs/PERFORMANCE.md "Pallas kernel layer"):

=================  ====================================================
flash_attention    blocked online-softmax attention (moved here from
                   ``ops/pallas_ops.py``; that module remains the op
                   registration shim)
opt_sgd/opt_adam   fused optimizer step — update+decay(+master cast)
                   in one kernel, wired into the ShardedTrainer update
                   rules (``parallel/opt_rules.py``)
int8_gemm          int8×int8→int32 GEMM with fused dequant+bias+relu
                   (the ``_contrib_quantized_*`` MXU path)
decode_attention   single-query flash against a padded KV cache (the
                   continuous-batching decode prerequisite)
twobit_compress /  2-bit gradient quantization with error feedback and
twobit_decompress  its rescale (kvstore gradient compression)
=================  ====================================================

Fallbacks LATCH: Pallas-unavailable is probed once per process and
warned once per family (the PR 11 native-probe pattern — no silent
per-call degradation), with every fallback event counted in
``mxtpu_kernels_fallback_total{family,reason}``. Dispatch decisions are
counted in ``mxtpu_kernels_dispatch_total{family,choice}`` and the
bucket keys feed distcheck pass 4 (cache-churn sweep), so an unstable
bucketing function is flagged exactly like an unstable compile key.
"""
from __future__ import annotations

import functools as _functools
import os
import threading

from . import table

__all__ = ["KernelEntry", "register_kernel", "entry", "families",
           "dispatch", "choice_for", "enabled", "pallas_available",
           "on_tpu", "dispatch_stats", "fallback_report", "token_salt",
           "reset_stats", "table"]

_FAMILIES: dict = {}
_lock = threading.Lock()
_stats: dict = {}          # family -> {"kernel": n, "xla": n, reasons: {}}
_warned_families = set()   # fallback warned once per family (latch)
_seen_buckets: dict = {}   # family -> set of bucket keys (distcheck pass 4)


class KernelEntry:
    """One registered op family (see module docstring for the fields)."""

    __slots__ = ("family", "kernel", "xla", "bucket", "supports",
                 "default_tpu", "tolerance")

    def __init__(self, family, kernel, xla, bucket, supports=None,
                 default_tpu=False, tolerance=""):
        self.family = family
        self.kernel = kernel
        self.xla = xla
        self.bucket = bucket
        self.supports = supports or (lambda *a, **k: True)
        self.default_tpu = bool(default_tpu)
        self.tolerance = tolerance


def register_kernel(family, *, kernel, xla, bucket, supports=None,
                    default_tpu=False, tolerance=""):
    """Register an op family. ``tolerance`` documents the kernel's
    numeric contract vs its XLA baseline (bit-exact, or the rtol/atol
    the tests assert)."""
    e = KernelEntry(family, kernel, xla, bucket, supports, default_tpu,
                    tolerance)
    _FAMILIES[family] = e
    return e


def entry(family):
    return _FAMILIES[family]


def families():
    """Registered family names, sorted (registry census)."""
    return sorted(_FAMILIES)


def enabled():
    """False when ``MXNET_TPU_KERNELS=0`` — every dispatch then takes
    the XLA baseline, restoring pre-kernel numerics bit-exactly."""
    return os.environ.get("MXNET_TPU_KERNELS", "1") != "0"


@_functools.lru_cache(maxsize=1)
def pallas_available():
    """Import-probe Pallas ONCE per process (the latch — never re-probe
    per call)."""
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401

        return True
    except ImportError:
        return False


@_functools.lru_cache(maxsize=1)
def on_tpu():
    """Backend probe, cached for the process lifetime (dispatch runs at
    trace time, but trace time is still a hot path for eager ops)."""
    try:
        import jax

        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _count(family, choice, reason):
    with _lock:
        rec = _stats.setdefault(family, {"kernel": 0, "xla": 0,
                                         "reasons": {}})
        rec[choice] += 1
        rec["reasons"][reason] = rec["reasons"].get(reason, 0) + 1
    try:
        from ..telemetry import registry as _registry

        _registry.counter(
            "mxtpu_kernels_dispatch_total",
            "Kernel-layer dispatch decisions", ("family", "choice")
        ).inc(1, family, choice)
    except Exception:
        pass


def _fallback(family, reason, detail=""):
    """Count (and once per family, warn about) a kernel->XLA fallback.
    Mirrors the native-IO probe pattern: the *reason* is cached and
    surfaced once, every later event is a counter bump only."""
    try:
        from ..telemetry import registry as _registry

        _registry.counter(
            "mxtpu_kernels_fallback_total",
            "Kernel-layer dispatches that fell back to the XLA baseline",
            ("family", "reason")).inc(1, family, reason)
    except Exception:
        pass
    if reason == "pallas_unavailable" and family not in _warned_families:
        _warned_families.add(family)
        try:
            from .. import log as _log

            _log.get_logger("mxnet_tpu.kernels").warning(
                "Pallas unavailable — kernel family %r permanently on "
                "the XLA baseline this process%s (see tools/diagnose.py "
                "'Kernels')", family, f" ({detail})" if detail else "")
        except Exception:
            pass


def _decide(e, args, kwargs, interpret):
    """(choice, reason, bucket) for one dispatch. Pure w.r.t. the traced
    values — only aval shapes/dtypes and process-level state feed it, so
    the decision is stable per shape bucket (and bakes into whatever
    executable is tracing us)."""
    if not enabled():
        return "xla", "env_disabled", None
    if not pallas_available():
        return "xla", "pallas_unavailable", None
    try:
        ok = e.supports(*args, **kwargs)
    except Exception:
        ok = False
    if not ok:
        return "xla", "unsupported_shape", None
    bucket = e.bucket(*args, **kwargs)
    # distcheck pass 4: dispatch keys must not churn — same workload,
    # same bucket. First sighting is the one legitimate "miss".
    try:
        from ..analysis import distcheck as _distcheck

        if _distcheck.CACHE_TRACK:
            seen = _seen_buckets.setdefault(e.family, set())
            _distcheck.cache_event("dispatch", f"kernels.{e.family}",
                                   bucket, bucket in seen)
            seen.add(bucket)
    except Exception:
        pass
    if interpret:
        # explicit interpreter request (tests, CPU numerics checks)
        return "kernel", "interpret_forced", bucket
    row = table.lookup(e.family, bucket)
    if row is not None:
        return row.get("winner", "xla"), "tuned", bucket
    if e.default_tpu and on_tpu():
        return "kernel", "untuned_default_tpu", bucket
    return "xla", "untuned_default", bucket


def dispatch(family, *args, interpret=None, **kwargs):
    """Route one call: the family's Pallas kernel where the dispatch
    table proved it faster (or ``interpret=True`` forces it), the XLA
    baseline everywhere else. Safe to call under a jit trace — the
    decision depends only on shapes and process state, so it is baked
    into the traced executable exactly like any other static argument."""
    e = _FAMILIES[family]
    choice, reason, _bucket = _decide(e, args, kwargs, interpret)
    _count(family, choice, reason)
    if choice == "kernel":
        # Pallas has no native CPU lowering: off-TPU the kernel runs in
        # the interpreter (numerics seam; opperf records such rows with
        # interpret=true so nobody mistakes them for a speed claim)
        run_interpret = bool(interpret) or not on_tpu()
        return e.kernel(*args, interpret=run_interpret, **kwargs)
    _fallback(family, reason)
    return e.xla(*args, **kwargs)


def choice_for(family, *args, **kwargs):
    """(choice, reason) dispatch WOULD make for these inputs — the
    introspection seam tests and diagnose use (no counters touched)."""
    e = _FAMILIES[family]
    choice, reason, _ = _decide(e, args, kwargs, None)
    return choice, reason


def dispatch_stats():
    """Per-family dispatch decision counts (process-local)."""
    with _lock:
        return {f: {"kernel": r["kernel"], "xla": r["xla"],
                    "reasons": dict(r["reasons"])}
                for f, r in sorted(_stats.items())}


def fallback_report():
    """Families latched onto the XLA baseline and why (diagnose)."""
    return {"pallas_available": pallas_available(),
            "warned_families": sorted(_warned_families),
            "enabled": enabled()}


def reset_stats():
    with _lock:
        _stats.clear()
    _seen_buckets.clear()


def token_salt():
    """Short hash of the dispatch state (enabled flag + table identity +
    entry winners) for folding into compile-service tokens: a dispatch
    change must produce a different executable identity, never a silent
    reuse of one traced under the old routing."""
    import hashlib
    import json as _json

    t = table.load()
    blob = _json.dumps({"enabled": enabled(),
                        "fp": t.get("fingerprint"),
                        "entries": t.get("entries", {})},
                       sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


# family registrations (import order is alphabetical, not load-bearing)
from . import flash  # noqa: E402,F401  (flash_attention)
from . import opt_step  # noqa: E402,F401  (opt_sgd / opt_adam)
from . import int8_gemm  # noqa: E402,F401  (int8_gemm)
from . import decode_attention  # noqa: E402,F401  (decode_attention)
from . import twobit  # noqa: E402,F401  (twobit_compress/_decompress)
