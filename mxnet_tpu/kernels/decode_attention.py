"""KV-cache decode attention — registry family ``decode_attention``.

The serving decode step attends ONE new query per sequence against a
padded KV cache: ``q (B, H, D)`` vs ``k/v (B, H, S, D)`` with a per-
sequence valid length. The dense XLA path materializes the (B, H, S)
score tensor in HBM and reads the whole padded cache; this kernel is
single-query flash — online softmax over k blocks held in VMEM, with
per-sequence lengths arriving through SMEM so fully-padded cache blocks
are skipped outright (the ROADMAP item 1 continuous-batching
prerequisite: decode cost tracks the *filled* cache, not the bucket).

Contract: ``(q, k, v, lengths int32 (B,), scale) -> (B, H, D)`` where
positions ``>= lengths[b]`` are masked out. ``lengths`` must be >= 1
per row (a zero-length sequence has no attention distribution; the
dense baseline NaNs on it too).

Tolerance vs the XLA baseline: f32 rtol=2e-5/atol=2e-5 (same softmax-
normalizer reassociation as flash_attention).
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

__all__ = ["decode_attention_reference"]


def decode_attention_reference(q, k, v, lengths, scale):
    """Dense masked single-query attention (the XLA dispatch baseline)."""
    s = jnp.einsum("bhd,bhkd->bhk", q, k) * scale
    smax = k.shape[2]
    mask = jnp.arange(smax)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", p, v)


def _decode_body(len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, *, scale, block_k, n_kb, n_heads):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = len_ref[bh // n_heads]

    # a block that starts at/after the valid length is pure padding —
    # skip it entirely (this is where decode cost stops tracking S_max)
    @pl.when(ki * block_k < seq_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32)        # (1, d)
        k_blk = k_ref[0].astype(jnp.float32)    # (block_k, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < seq_len, s, -jnp.inf)
        m = m_ref[...]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def _kernel(q, k, v, lengths, scale, block_k=128, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    smax = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, 1, d)
    k3 = k.reshape(bh, smax, d)
    v3 = v.reshape(bh, smax, d)
    n_kb = smax // block_k
    grid = (bh, n_kb)
    body = _functools.partial(_decode_body, scale=float(scale),
                              block_k=int(block_k), n_kb=n_kb,
                              n_heads=h)
    out = pl.pallas_call(
        body,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths (B,)
            pl.BlockSpec((1, 1, d), lambda i, kk: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, kk: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(lengths.astype(jnp.int32), q3, k3, v3)
    return out.reshape(b, h, d)


def _xla(q, k, v, lengths, scale, block_k=128):
    del block_k
    return decode_attention_reference(q, k, v, lengths, scale)


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


def _bucket(q, k, v, lengths, scale, block_k=128):
    b, h, d = q.shape
    return (f"bh{_pow2(b * h)}_s{_pow2(k.shape[2])}_d{d}_"
            f"{jnp.dtype(q.dtype).name}_k{block_k}")


def _supports(q, k, v, lengths, scale, block_k=128):
    if q.ndim != 3 or k.ndim != 4:
        return False
    d, smax = q.shape[2], k.shape[2]
    return (smax % block_k == 0 and d % 8 == 0 and 0 < d <= 512
            and lengths.ndim == 1 and lengths.shape[0] == q.shape[0])


def _register():
    from . import register_kernel

    register_kernel(
        "decode_attention", kernel=_kernel, xla=_xla, bucket=_bucket,
        supports=_supports, default_tpu=True,
        tolerance="f32 rtol=2e-5 atol=2e-5 vs dense masked softmax "
                  "(normalizer reassociated across k blocks)")


_register()
