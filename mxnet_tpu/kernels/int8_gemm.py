"""int8 GEMM with fused dequant+bias+relu — registry family ``int8_gemm``.

PR 14's ``_contrib_quantized_fully_connected`` lowers to a bare
``lax.dot_general`` whose int8 operands scalarize on CPU and whose
dequant/bias epilogue XLA may or may not fuse; this kernel feeds the MXU
int8×int8→int32 tiles directly and applies the per-output-channel
dequantize, bias add and optional relu while the accumulator tile is
still in VMEM — the epilogue never round-trips through HBM.

Contract: ``(qx int8 (M, K), weight int8 (N, K), scale_eff f32 scalar or
(N,)) -> f32 (M, N)`` where ``out = (qx @ weight.T).astype(f32) *
scale_eff [+ bias] [relu]``. ``scale_eff`` is the folded activation ×
weight scale (``s_x * scale`` from the quantized FC op).

Tolerance vs the XLA baseline: BIT-EXACT. The int32 accumulation is
exact in both paths and the f32 epilogue is the same op order
(scale-multiply, then bias add, then max(·, 0)); tests assert ``==``
against the PR 14 fused op output.
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

_BN = 128   # output-channel block (lane dim)
_BK = 128   # reduction block


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _gemm_body(x_ref, w_ref, sc_ref, b_ref, o_ref, acc_ref, *, n_kb,
               relu):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        out = acc_ref[...].astype(jnp.float32) * sc_ref[...]
        out = out + b_ref[...]
        if relu:
            out = jnp.maximum(out, 0.0)
        o_ref[...] = out


def _kernel(qx, weight, scale_eff, bias=None, relu=False,
            interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = qx.shape
    n = weight.shape[0]
    bm = 128 if m >= 128 else 32  # int8 min sublane tile is 32
    x = _pad_to(_pad_to(qx, 0, bm), 1, _BK)
    w = _pad_to(_pad_to(weight, 0, _BN), 1, _BK)
    mp, kp = x.shape
    np_ = w.shape[0]
    sc = jnp.broadcast_to(
        jnp.asarray(scale_eff, jnp.float32).reshape(-1), (n,))
    sc = _pad_to(sc, 0, _BN).reshape(1, np_)
    if bias is None:
        b = jnp.zeros((1, np_), jnp.float32)
    else:
        b = _pad_to(bias.astype(jnp.float32).reshape(-1), 0,
                    _BN).reshape(1, np_)
    n_kb = kp // _BK
    grid = (mp // bm, np_ // _BN, n_kb)
    out = pl.pallas_call(
        _functools.partial(_gemm_body, n_kb=n_kb, relu=bool(relu)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, _BK), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((_BN, _BK), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((1, _BN), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, _BN), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, _BN), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, _BN), jnp.int32)],
        interpret=interpret,
    )(x, w, sc, b)
    return out[:m, :n]


def _xla(qx, weight, scale_eff, bias=None, relu=False):
    """The PR 14 path verbatim: bare dot_general + unfused epilogue."""
    acc = jax.lax.dot_general(
        qx, weight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * scale_eff
    if bias is not None:
        out = out + bias
    if relu:
        out = jnp.maximum(out, 0.0)
    return out


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


def _bucket(qx, weight, scale_eff, bias=None, relu=False):
    m, k = qx.shape
    n = weight.shape[0]
    return (f"m{_pow2(m)}_n{_pow2(n)}_k{_pow2(k)}_"
            f"bias{int(bias is not None)}_relu{int(bool(relu))}")


def _supports(qx, weight, scale_eff, bias=None, relu=False):
    if qx.ndim != 2 or weight.ndim != 2:
        return False
    i8 = jnp.dtype(jnp.int8)
    if jnp.dtype(qx.dtype) != i8 or jnp.dtype(weight.dtype) != i8:
        return False
    return qx.shape[1] == weight.shape[1] and qx.size > 0


def _register():
    from . import register_kernel

    register_kernel(
        "int8_gemm", kernel=_kernel, xla=_xla, bucket=_bucket,
        supports=_supports,
        tolerance="bit-exact vs the PR 14 dot_general+epilogue path "
                  "(exact int32 accumulation, same f32 epilogue order)")


_register()
