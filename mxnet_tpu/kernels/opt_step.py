"""Fused optimizer step — registry families ``opt_sgd`` / ``opt_adam``.

The reference's optimizer layer is per-op CUDA (optimizer_op-inl.h
SGDMomKernel / AdamUpdateKernel); our XLA baseline is already one fused
executable per step, so the win here is tighter: one Pallas program
reads weight+grad+state tiles from VMEM once and writes the updated
tensors, with the learning rate arriving through SMEM (a traced scalar —
LR schedules never force a retrace). ``parallel/opt_rules.py`` routes
the ShardedTrainer's sgd(momentum) and adam rules through
``kernels.dispatch`` so the step timeline's optimizer phase stays folded
into compute and the update itself stops being XLA's guess.

Tensors of any shape are flattened and padded to (rows, 128) lanes —
the f32 VPU tile — and the grid walks row blocks; padding lanes compute
garbage that is sliced off (all operations are non-signalling on zeros).

Tolerance vs the XLA baseline: BIT-EXACT for f32 tensors. The kernel
body is the same IEEE op sequence as ``ops/optimizer_op.py``
(rescale → clip → momentum/moment update → weight update) evaluated in
f32; tests assert equality with ``==``, not allclose. Non-f32 weights
(the multi-precision bf16 path) fall back to XLA — the baseline computes
those in input dtype and a kernel would not match it bitwise.
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

_LANES = 128       # f32 VPU lane width
_BLOCK_ROWS = 256  # rows per grid step: 256*128*4B = 128 KiB per operand


def _pad_rows(n):
    rows = -(-n // _LANES)
    return -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS


def _to_tiles(x):
    """Flatten to (padded_rows, 128) f32 lanes."""
    flat = x.reshape(-1)
    rows = _pad_rows(flat.size)
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES)


def _from_tiles(t, shape, size):
    return t.reshape(-1)[:size].reshape(shape)


def _prep(g_ref, rescale, clip):
    g = g_ref[...] * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def _sgd_mom_body(lr_ref, w_ref, g_ref, m_ref, w_out, m_out, *,
                  momentum, wd, rescale, clip):
    lr = lr_ref[0, 0]
    w = w_ref[...]
    g = _prep(g_ref, rescale, clip)
    m_new = momentum * m_ref[...] - lr * (g + wd * w)
    w_out[...] = w + m_new
    m_out[...] = m_new


def _adam_body(lr_ref, w_ref, g_ref, mean_ref, var_ref, w_out, mean_out,
               var_out, *, beta1, beta2, epsilon, wd, rescale, clip):
    lr = lr_ref[0, 0]
    w = w_ref[...]
    # adam-family prep: wd*weight folds in BEFORE the clip
    # (optimizer_op._prep_grad_wd — ordering is part of the bit contract)
    g = g_ref[...] * rescale + wd * w
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    mean_new = beta1 * mean_ref[...] + (1 - beta1) * g
    var_new = beta2 * var_ref[...] + (1 - beta2) * jnp.square(g)
    w_out[...] = w - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    mean_out[...] = mean_new
    var_out[...] = var_new


def _run(body, lr, tensors, n_out, interpret):
    """Common pallas_call plumbing: SMEM scalar lr + row-blocked VMEM
    operands, one output struct per updated tensor."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape, size = tensors[0].shape, tensors[0].size
    tiles = [_to_tiles(t) for t in tensors]
    rows = tiles[0].shape[0]
    grid = (rows // _BLOCK_ROWS,)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    outs = pl.pallas_call(
        body,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [blk] * len(tiles),
        out_specs=[blk] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
                   for _ in range(n_out)],
        interpret=interpret,
    )(lr_arr, *tiles)
    return tuple(_from_tiles(o, shape, size) for o in outs)


# ---- registry wiring -------------------------------------------------

def _kernel_sgd(w, g, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, interpret=False):
    body = _functools.partial(_sgd_mom_body, momentum=float(momentum),
                              wd=float(wd), rescale=float(rescale_grad),
                              clip=float(clip_gradient))
    return _run(body, lr, [w, g, mom], 2, interpret)


def _xla_sgd(w, g, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
             clip_gradient=-1.0):
    from ..ops import optimizer_op as _op

    return _op.sgd_mom_update.fn(
        w, g, mom, lr=lr, momentum=momentum, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)


def _kernel_adam(w, g, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, interpret=False):
    body = _functools.partial(_adam_body, beta1=float(beta1),
                              beta2=float(beta2), epsilon=float(epsilon),
                              wd=float(wd), rescale=float(rescale_grad),
                              clip=float(clip_gradient))
    return _run(body, lr, [w, g, mean, var], 3, interpret)


def _xla_adam(w, g, mean, var, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
              wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    from ..ops import optimizer_op as _op

    return _op.adam_update.fn(
        w, g, mean, var, lr=lr, beta1=beta1, beta2=beta2,
        epsilon=epsilon, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)


def _bucket(w, *rest, **kw):
    """Element-count bucket (pow2): the kernel is elementwise over the
    flattened weight, so only the padded tile count and dtype matter."""
    n = 1
    for s in w.shape:
        n *= s
    p = 1
    while p < n:
        p *= 2
    return f"n{p}_{jnp.dtype(w.dtype).name}"


def _supports(w, *tensors_then_lr, **kw):
    """f32 tensors only (the bit-exactness contract) with a scalar lr
    and static-float hyperparameters (they bake into the kernel body);
    anything else — e.g. the bf16 multi-precision path or a traced wd —
    stays on XLA."""
    *tensors, lr = tensors_then_lr
    if jnp.ndim(lr) != 0:
        return False
    if w.size == 0:
        return False
    for v in kw.values():
        if v is not None and not isinstance(v, (bool, int, float)):
            return False
    f32 = jnp.dtype(jnp.float32)
    return all(jnp.dtype(t.dtype) == f32 for t in (w, *tensors))


def _register():
    from . import register_kernel

    tol = ("bit-exact vs ops/optimizer_op.py for f32 tensors (same IEEE "
           "op order); non-f32 falls back to XLA")
    register_kernel("opt_sgd", kernel=_kernel_sgd, xla=_xla_sgd,
                    bucket=_bucket, supports=_supports, tolerance=tol)
    register_kernel("opt_adam", kernel=_kernel_adam, xla=_xla_adam,
                    bucket=_bucket, supports=_supports, tolerance=tol)


_register()
