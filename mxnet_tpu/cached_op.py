"""CachedOp: the trace-to-XLA compiled-graph unit behind ``hybridize()``.

Parity target: `src/imperative/cached_op.cc` — the reference caches forward
and backward nnvm graphs keyed on input shapes, plans memory, pre-creates
engine ops (static mode), and records ONE autograd tape node for the whole
call (`CachedOp::Forward` :762, `Backward` :990).

TPU-native redesign: "build graph + plan memory + bulk ops" collapses into
XLA compilation. The block's imperative ``forward`` is traced by ``jax.jit``
into a single executable per (input-signature, training-mode) key:

  * static_alloc/static_shape modes are subsumed — XLA always plans memory
    statically per executable; the shape-keyed cache replaces bucketing.
  * the backward graph is a second cached executable computing the VJP with
    rematerialisation (the forward is recomputed inside the backward — the
    reference's `MXNET_BACKWARD_DO_MIRROR` idea, which is the right default
    on TPU where HBM is the bottleneck and FLOPs are cheap).
  * mutable layer state (BatchNorm running stats) is threaded functionally:
    traced updates are captured by a TraceScope and returned as extra
    executable outputs, then rebound into the owning NDArray handles — the
    analogue of the reference's aux-state writeback.
  * PRNG (Dropout) keys are explicit executable inputs drawn from the global
    stateful stream per call, so compiled randomness still advances with
    `mx.random.seed` (reference: per-device Resource kRandom).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import autograd
from .analysis import distcheck as _distcheck

__all__ = ["CachedOp", "current_trace", "update_state"]

_tls = threading.local()


def current_trace():
    """The innermost active TraceScope, or None (imperative mode)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class TraceScope:
    """Active while a CachedOp trace runs: supplies split PRNG keys and
    collects functional state updates."""

    def __init__(self, rng_key):
        self._key = rng_key
        self.state_updates: List[Tuple[Any, Any]] = []  # (NDArray handle, raw)

    def next_key(self):
        import jax

        self._key, sub = jax.random.split(self._key)
        return sub

    def record_state_update(self, handle, raw_value):
        # last write wins per handle (matches in-place update ordering)
        for i, (h, _) in enumerate(self.state_updates):
            if h is handle:
                self.state_updates[i] = (handle, raw_value)
                return
        self.state_updates.append((handle, raw_value))

    def __enter__(self):
        if not hasattr(_tls, "stack"):
            _tls.stack = []
        _tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()


def update_state(handle, new_value):
    """Write a stateful buffer (running stats): immediate in imperative mode,
    captured functionally during a trace."""
    new_raw = new_value._data if hasattr(new_value, "_data") else new_value
    scope = current_trace()
    if scope is not None:
        scope.record_state_update(handle, new_raw)
    else:
        handle._rebind(new_raw)


# ------------------------------------------------------------ structures ---

def _flatten(obj, arrays, spec):
    """Flatten nested (lists/tuples of) NDArrays; non-arrays become static
    leaves baked into the cache key."""
    from .ndarray import NDArray

    if isinstance(obj, NDArray):
        spec.append("A")
        arrays.append(obj)
    elif isinstance(obj, (list, tuple)):
        spec.append(("L" if isinstance(obj, list) else "T", len(obj)))
        for it in obj:
            _flatten(it, arrays, spec)
    else:
        spec.append(("S", obj))
    return arrays, spec


def _unflatten_build(spec, values, pos=0, idx=0):
    kind = spec[pos]
    if kind == "A":
        return values[idx], pos + 1, idx + 1
    if isinstance(kind, tuple) and kind[0] in ("L", "T"):
        n = kind[1]
        out = []
        pos += 1
        for _ in range(n):
            item, pos, idx = _unflatten_build(spec, values, pos, idx)
            out.append(item)
        return (out if kind[0] == "L" else tuple(out)), pos, idx
    # static leaf
    return kind[1], pos + 1, idx


class CachedOp:
    """Compile-and-cache wrapper around an imperative forward function.

    ``forward_fn(*args)`` must be a function of NDArrays (nested lists ok)
    that reads parameters through the NDArray handles in ``params`` —
    exactly what a HybridBlock's forward does. Handles listed in ``states``
    may be written via ``update_state`` (running stats).
    """

    def __init__(self, forward_fn: Callable, params: Optional[List] = None,
                 flags=()):
        self._fn = forward_fn
        self._param_handles = list(params or [])
        self._flags = dict(flags) if flags else {}
        self._cache: Dict = {}   # key -> (fwd_jit, bwd_jit, state_handles, out_spec)
        self._uses_rng = True    # conservatively thread a key; cheap if unused
        # recompile-churn call-site identity (analysis.distcheck pass 4):
        # the signature cache below keys on input SHAPES, so per-step
        # shape drift shows up as distinct keys at this site
        self._site = "CachedOp[%s]" % getattr(
            forward_fn, "__qualname__", type(forward_fn).__name__)
        # process-stable identity for the unified compile service's
        # persistent cache: source hash of the forward + repr of the
        # bound instance (a gluon block's repr encodes its layer
        # structure and hyper-params, which the traced computation bakes
        # in but input/param shapes alone cannot distinguish)
        import hashlib

        ident = []
        try:
            import inspect

            ident.append(inspect.getsource(
                getattr(forward_fn, "__func__", forward_fn)))
        except (OSError, TypeError):
            pass
        inst = getattr(forward_fn, "__self__", None)
        if inst is not None:
            ident.append(repr(inst))
        self._token_src = hashlib.sha1(
            "\n".join(ident).encode()).hexdigest()[:12] if ident else "nosrc"

    # -------------------------------------------------------------- call ---
    def __call__(self, *args):
        from . import profiler as _profiler
        from .ndarray import NDArray

        prof_t0 = _profiler._now_us() if _profiler._REC_SYMBOLIC else None
        arrays, spec = _flatten(list(args), [], [])
        in_raws = [a._data for a in arrays]
        params = self._param_handles
        param_raws = [p._data for p in params]
        if _distcheck.DONATED:
            # use-after-donate: stale aliases of donated buffers fail
            # here, named, before they reach the compiled executable
            _distcheck.check_live(in_raws + param_raws, self._site)
        training = autograd.is_training()
        from . import _amp_core

        if _amp_core.cache_stale(self):
            self._cache.clear()
        from .ops.registry import dtype_str as _dt

        key = (tuple(spec_key(s) for s in spec),
               tuple((tuple(r.shape), _dt(r.dtype)) for r in in_raws),
               tuple((tuple(r.shape), _dt(r.dtype)) for r in param_raws),
               training)
        entry = self._cache.get(key)
        if _distcheck.CACHE_TRACK:
            _distcheck.cache_event("cachedop", self._site, key,
                                   entry is not None)
        if entry is None:
            entry = self._build(key, spec, arrays, params, training)
            self._cache[key] = entry
        fwd_jit, bwd_jit, state_handles, n_outs, out_spec = entry

        from . import random as _rand

        rng = _rand.next_key()

        recording = autograd.is_recording() and (
            any(p._grad_req != "null" for p in params)
            or autograd.any_on_tape(arrays))
        outs_and_state = fwd_jit(tuple(in_raws), tuple(param_raws), rng)
        out_raws = outs_and_state[:n_outs]
        state_raws = outs_and_state[n_outs:]
        with autograd.pause():
            for h, raw in zip(state_handles, state_raws):
                h._rebind(raw)

        wrapped = [NDArray(r) for r in out_raws]
        if recording:
            diff_inputs = list(arrays) + list(params)
            entries = autograd.make_entries(diff_inputs)

            ins_c, ps_c = tuple(in_raws), tuple(param_raws)

            def vjp_fn(cots, _bwd=bwd_jit, _ins=ins_c, _ps=ps_c, _rng=rng):
                cots = cots if isinstance(cots, tuple) else (cots,)
                din, dps = _bwd(_ins, _ps, _rng, tuple(cots))
                return tuple(din) + tuple(dps)

            node = autograd.TapeNode(
                "CachedOp", vjp_fn, entries, n_outs,
                [tuple(r.shape) for r in out_raws],
                [r.dtype for r in out_raws])
            for i, w in enumerate(wrapped):
                w._tape_node = node
                w._tape_index = i
        result, _, _ = _unflatten_build(out_spec, wrapped)
        if prof_t0 is not None:
            _profiler.record_event("CachedOp", prof_t0,
                                   _profiler._now_us() - prof_t0,
                                   cat="symbolic")
        return result

    # ------------------------------------------------------------- build ---
    def _build(self, key, spec, arrays, params, training):
        import jax

        from .ndarray import NDArray

        fn = self._fn
        param_handles = params
        state_handles_box: List = []
        out_spec_box: List = []
        n_outs_box: List = []

        def run_traced(in_raws, param_raws, rng):
            """Re-entrant traced body: swap traced values into the param
            handles, run the imperative forward, collect state updates."""
            saved = [(p, p._data) for p in param_handles]
            scope = TraceScope(rng)
            try:
                for p, traced in zip(param_handles, param_raws):
                    p._data = traced
                nd_in = [NDArray(r) for r in in_raws]
                rebuilt, _, _ = _unflatten_build(spec, nd_in)
                with scope, autograd.pause(train_mode=training):
                    out = fn(*rebuilt)
            finally:
                for p, orig in saved:
                    p._data = orig
            out_arrays, ospec = _flatten(out, [], [])
            state_pairs = scope.state_updates
            return ([o._data for o in out_arrays],
                    [raw for _, raw in state_pairs],
                    [h for h, _ in state_pairs], ospec)

        # one eager-style trace via eval_shape? No — trace directly in jit.
        # The first jit call performs the trace; capture metadata via boxes.
        def pure(in_raws, param_raws, rng):
            outs, states, handles, ospec = run_traced(in_raws, param_raws, rng)
            if not state_handles_box:
                state_handles_box.append(handles)
                out_spec_box.append(ospec)
                n_outs_box.append(len(outs))
            return tuple(outs) + tuple(states)

        from . import compile as _compile

        token = ("cachedop", self._site, self._token_src, key)
        fwd_jit = _compile.jit(pure, site="cachedop",
                               token=token + ("fwd",))
        # abstract trace now so the metadata boxes fill; compilation happens
        # on first real call. The service keys executables on argument
        # placement/sharding, so reset_ctx still recompiles per placement
        # (the reason this was never lower().compile() before the seam).
        in_shapes = [jax.ShapeDtypeStruct(a._data.shape, a._data.dtype)
                     for a in arrays]
        p_shapes = [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                    for p in params]
        rng_spec = jax.ShapeDtypeStruct((2,), "uint32")
        try:
            jax.eval_shape(pure, tuple(in_shapes), tuple(p_shapes), rng_spec)
        except Exception:
            # e.g. a different rng key format: trace concretely instead
            pure(tuple(a._data for a in arrays),
                 tuple(p._data for p in params), _dummy_key())

        n_outs = n_outs_box[0]
        state_handles = state_handles_box[0]
        out_spec = out_spec_box[0]

        def diff_only(in_raws, param_raws, rng):
            res = pure(in_raws, param_raws, rng)
            return res[:n_outs]

        def bwd(in_raws, param_raws, rng, cots):
            _, pull = jax.vjp(lambda i, p: diff_only(i, p, rng),
                              in_raws, param_raws)
            return pull(tuple(cots))

        bwd_jit = _compile.jit(bwd, site="cachedop",
                               token=token + ("bwd",))
        return fwd_jit, bwd_jit, state_handles, n_outs, out_spec


def _dummy_key():
    import jax

    return jax.random.PRNGKey(0)


def spec_key(s):
    """Hashable form of one spec element."""
    if isinstance(s, tuple) and s[0] == "S":
        try:
            hash(s[1])
            return s
        except TypeError:
            return ("S", repr(s[1]))
    return s
