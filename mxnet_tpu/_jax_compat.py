"""Version-compat shims for jax APIs that moved between releases.

The repo targets a range of jax versions (the CI image pins one, user
environments another); these helpers resolve the few symbols whose home
moved so call sites stay version-agnostic:

* ``enable_x64`` — ``jax.enable_x64`` (new) vs
  ``jax.experimental.enable_x64`` (<= 0.4.x).
* ``shard_map`` — ``jax.shard_map`` (new) vs
  ``jax.experimental.shard_map.shard_map`` (<= 0.4.x). Signatures are
  identical (fn, mesh=, in_specs=, out_specs=).

Import cost is paid lazily: nothing here touches jax until first use.
"""
from __future__ import annotations

__all__ = ["enable_x64", "get_shard_map", "pcast"]


def enable_x64():
    """Context manager scoping 64-bit dtype semantics, wherever this jax
    version keeps it."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx()


def get_shard_map():
    """The shard_map transform, wherever this jax version keeps it."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    import functools

    from jax.experimental.shard_map import shard_map as _esm

    # the old replication checker mis-types scan carries (jax#21236-era
    # behaviour; its own error message recommends check_rep=False) — the
    # new versions replaced it with the vma system, so disabling it here
    # only drops a diagnostic, not a semantic
    return functools.partial(_esm, check_rep=False)


def pcast(x, axis_name, to="varying"):
    """``jax.lax.pcast`` where it exists (the varying-type marker of the
    new shard_map vma system); identity on jax versions whose shard_map
    predates varying-type checking (nothing to mark there)."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_name, to=to)
