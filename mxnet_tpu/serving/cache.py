"""Content-addressed prediction cache — dedupe the hot-key traffic.

Real millions-of-users serving traffic is heavily skewed: a small set of
hot inputs (trending item, default homepage query) accounts for a large
fraction of requests. Recomputing an identical prediction burns a batch
slot and a bucket's worth of padded FLOPs for an answer that is fully
determined by ``(model version, input bytes)`` — served models are pure
functions of their pinned parameters.

The cache sits IN FRONT of the batcher (:meth:`BucketBatcher.submit`
checks it before admission), so a hit never touches the queue, the
coalescing window, or the device: it fulfils the future immediately on
the submit thread. That is what makes the hit path ~memcpy-speed while
the compute path pays queue + h2d + XLA.

Correctness is carried entirely by the key::

    key = (model name, model version at lookup, sha1 of dtype/shape/bytes)

and by an insert-side guard: a result is only inserted under the version
that actually COMPUTED it (``ServedModel.run_versioned`` reports the
pinned version it read). When the model bus flips the served version the
old entries' keys simply stop being generated — ``invalidate()`` also
drops them eagerly so memory isn't held by a dead generation, but the
staleness proof does not depend on eager invalidation: a stale entry is
*unreachable*, not merely evicted.

Bounded LRU (``serving.config`` ``cache_entries``), thread-safe, and
observable: hits/misses/insertions/evictions/invalidations flow into
``mxtpu_serving_cache_*`` via the telemetry exporter.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["PredictionCache", "content_key"]


def content_key(model, version, arr):
    """The content address of one request row-block: model name x served
    version x input bytes (dtype and shape ride inside the hash so a
    reshaped or recast input never aliases). Returns a small str."""
    h = hashlib.sha1()
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return f"{model}@{version}:{h.hexdigest()}"


def _copy(value):
    """Defensive copy of a fulfilment value (one array, or a list of
    arrays for multi-output models) — cached entries must never alias a
    caller's buffer."""
    if isinstance(value, (list, tuple)):
        return [np.array(v, copy=True) for v in value]
    return np.array(value, copy=True)


class PredictionCache:
    """Bounded LRU over content keys for one model's predictions."""

    def __init__(self, capacity=4096):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._data = OrderedDict()       # key -> (np result, version)
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0
        self.invalidations = 0
        self._version = None             # last version seen (flip detect)

    # ---------------------------------------------------------- lookup ---
    def get(self, key):
        """The cached prediction for ``key`` (a copy — callers mutate
        results freely) or None. Counts the hit/miss."""
        with self._lock:
            hit = self._data.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return _copy(hit[0])

    def put(self, key, value, version):
        """Insert ``value`` computed by ``version``. The caller passes
        the version that RAN the batch (run_versioned's report) and the
        key it admitted under; a mismatch means the model flipped while
        the request was in flight — the result is still correct for its
        key, but the key names the OLD version so inserting it can never
        serve stale data under the new one. Eldest entries fall off past
        capacity."""
        val = _copy(value)
        with self._lock:
            if self._version is None:
                self._version = version
            elif version != self._version:
                # served version flipped: drop the dead generation now
                self._version = version
                self._invalidate_locked()
            if key in self._data:
                self._data.move_to_end(key)
                self._data[key] = (val, version)
                return
            self._data[key] = (val, version)
            self.insertions += 1
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    # ------------------------------------------------------ invalidate ---
    def _invalidate_locked(self):
        n = len(self._data)
        self._data.clear()
        if n:
            self.invalidations += n

    def invalidate(self, version=None):
        """Drop everything (model-bus version flip / rollout). With a
        ``version`` the new generation is remembered so put() stops
        re-invalidating. Returns how many entries were dropped."""
        with self._lock:
            n = len(self._data)
            self._invalidate_locked()
            if version is not None:
                self._version = version
        return n

    def observe_version(self, version):
        """Cheap flip detector for the submit path: when the served
        version moved since the last call, invalidate. Lookup keys carry
        the version so this is belt-and-braces for memory, not for
        correctness."""
        with self._lock:
            if self._version is None:
                self._version = version
            elif version != self._version:
                self._version = version
                self._invalidate_locked()

    # ----------------------------------------------------------- state ---
    def __len__(self):
        with self._lock:
            return len(self._data)

    def stats(self):
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._data),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": round(self.hits / total, 4) if total else None,
                "insertions": self.insertions,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "version": self._version,
            }
