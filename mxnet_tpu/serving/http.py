"""Minimal HTTP/JSON front end over a ModelServer.

Endpoints (TF-Serving-flavoured paths, JSON bodies)::

    POST /v1/models/<name>:predict   {"data": [[...], ...],
                                      "priority": "interactive"|"batch",
                                      "deadline_ms": <F>}
                                     (priority and deadline_ms optional:
                                     the QoS class and per-request
                                     deadline ride INSIDE the body so
                                     they survive the fleet router's
                                     opaque forward + hedge unchanged)
                                     -> {"model":..., "outputs": [[...]],
                                     "model_version":...,
                                     "request_id":..., "phases": {...}}
                                     ("model_version" is the model-bus
                                     version the answering batch ran
                                     under — 0 until a live weight
                                     update lands; docs/SERVING.md
                                     "Online updates")
                                     (request id from the caller's
                                     X-Request-Id header or minted here,
                                     echoed back as a header; "phases"
                                     is the traced queue_wait /
                                     batch_collect / h2d / compute /
                                     respond breakdown when tracing is
                                     on — docs/OBSERVABILITY.md)
    GET  /v1/models                  -> {"models": [...]}
    GET  /v1/stats                   -> ModelServer.stats()
    GET  /healthz                    -> {"status": "ok"|"draining"}
    GET  /metrics                    -> Prometheus text format: the full
                                     telemetry registry (serving rps /
                                     latency / queue depth, compile-cache
                                     hits/misses, watchdog stalls, device
                                     memory — mxnet_tpu.telemetry.export)
    GET  /metrics.json               -> the same registry as JSON

Error mapping — the typed serving errors become the status codes a
load balancer expects: unknown model 404, admission fast-reject 429
(with Retry-After), draining 503, request deadline 504 (both the
client-wait RequestTimeout and a DeadlineExceeded drop — the latter
with ``"dropped": true`` since no compute ran), failed batch 500.

This front end exists so external clients (and ``tools/loadgen.py``'s
socket mode) can drive the server; the throughput path is the
in-process API. Serving a request is one bounded ``server.predict`` —
the handler threads (ThreadingHTTPServer) never wait unbounded.
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from ..telemetry import trace as _trace
from .errors import (DeadlineExceeded, ModelNotFound, RequestError,
                     RequestTimeout, ServerBusyError, ServerDrainingError)

__all__ = ["HttpFrontEnd"]

_PREDICT_RE = re.compile(r"^/(?:v1/models|models|predict)/([^/:]+)"
                         r"(?::predict)?$")


class HttpFrontEnd:
    """Bind a ModelServer to a local HTTP port (``port=0`` picks one)."""

    def __init__(self, server, host="127.0.0.1", port=0, timeout=None):
        self._server = server
        self._timeout = timeout
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "mxtpu-serving/0.1"
            # keep-alive clients (the fleet router's persistent upstream
            # connections, loadgen's KeepAliveClient) otherwise hit the
            # Nagle x delayed-ACK 40ms stall on every request
            disable_nagle_algorithm = True

            def log_message(self, *args):  # stay quiet under load
                pass

            def _json(self, code, payload, extra_headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _text(self, code, text, ctype):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                srv = front._server
                if self.path == "/healthz":
                    self._json(200, {"status": "draining" if srv.draining
                                     else "ok"})
                elif self.path in ("/v1/models", "/models"):
                    # "detail" carries per-model dtype/weight_dtype (int8
                    # for quantized models) + the bucket ladder
                    self._json(200, {"models": srv.models(),
                                     "detail": srv.model_info()})
                elif self.path in ("/v1/stats", "/stats"):
                    self._json(200, srv.stats())
                elif self.path == "/metrics":
                    from ..telemetry import export as _export

                    self._text(200, _export.render_prometheus(),
                               _export.PROMETHEUS_CONTENT_TYPE)
                elif self.path == "/metrics.json":
                    from ..telemetry import export as _export

                    self._text(200, _export.render_json(),
                               "application/json")
                else:
                    self._json(404, {"error": f"no route {self.path!r}"})

            def do_POST(self):
                srv = front._server
                m = _PREDICT_RE.match(self.path)
                if not m:
                    self._json(404, {"error": f"no route {self.path!r}"})
                    return
                name = m.group(1)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    arr = _np.asarray(payload["data"])
                    priority = payload.get("priority", "interactive")
                    deadline_ms = payload.get("deadline_ms")
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                except (ValueError, KeyError, TypeError) as e:
                    self._json(400, {"error": f"bad request body: {e}"})
                    return
                # propagated request id: honour the caller's
                # X-Request-Id, else mint one; the batcher picks it up
                # through the thread-bound trace context and the span
                # pipeline keys the whole request timeline on it
                rid = self.headers.get("X-Request-Id") \
                    or _trace.new_request_id()
                rid_hdr = [("X-Request-Id", rid)]
                try:
                    with _trace.context(rid):
                        fut = srv.submit(name, arr, priority=priority,
                                         deadline_ms=deadline_ms)
                    out = fut.result(front._timeout)
                except ModelNotFound as e:
                    self._json(404, {"error": str(e)},
                               extra_headers=rid_hdr)
                except ServerDrainingError as e:
                    self._json(503, {"error": str(e)},
                               extra_headers=rid_hdr
                               + [("Retry-After", "1")])
                except ServerBusyError as e:
                    self._json(429, {"error": str(e)},
                               extra_headers=rid_hdr
                               + [("Retry-After", "0.1")])
                except DeadlineExceeded as e:
                    # the cheap 504: the request was DROPPED before any
                    # compute, so a hedging/retrying client knows no
                    # batch slot was burned on it
                    self._json(504, {"error": str(e), "dropped": True},
                               extra_headers=rid_hdr)
                except RequestTimeout as e:
                    self._json(504, {"error": str(e)},
                               extra_headers=rid_hdr)
                except (RequestError, ValueError) as e:
                    code = 400 if isinstance(e, ValueError) else 500
                    self._json(code, {"error": str(e)},
                               extra_headers=rid_hdr)
                else:
                    outs = out if isinstance(out, list) else [out]
                    body = {"model": name,
                            "outputs": [o.tolist() for o in outs],
                            "model_version": fut.model_version,
                            "request_id": fut.request_id or rid}
                    if fut.cache_hit:
                        body["cache_hit"] = True
                    bd = fut.breakdown()
                    if bd is not None:
                        body["phases"] = {
                            k: bd.get(f"{k}_ms")
                            for k in _trace.REQUEST_PHASES}
                        body["phases"]["total_ms"] = bd["total_ms"]
                    self._json(200, body, extra_headers=rid_hdr)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
                daemon=True, name="mxtpu-serving-http")
            self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
