"""ServedModel / ModelContainer: N models, each pre-compiled at a small
set of padded batch buckets through the unified compile service.

A :class:`ServedModel` wraps one inference function as a pure
``fwd(param_raws, aux_raws, x)`` callable compiled via
:func:`mxnet_tpu.compile.jit` under the ``serving`` site with a
process-stable token — so every bucket executable lands in the
persistent disk cache, records a warmup-manifest entry, and shows up in
``compile.stats()``/churn reports. A warm pod therefore starts with
:func:`mxnet_tpu.compile.warmup` + :meth:`ModelContainer.warmup` and
serves its whole bucket ladder with ZERO recompiles.

Loaders (the same model zoo the C predict ABI speaks):

* :meth:`ServedModel.from_block` — a gluon (Hybrid)Block with
  materialized parameters (the ``capi_bridge``/SymbolBlock surface),
* :meth:`ServedModel.from_symbol` — a Symbol + arg/aux param dicts,
* :meth:`ServedModel.from_checkpoint` — ``prefix-symbol.json`` +
  ``prefix-%04d.params`` (``model.load_checkpoint``),
* :meth:`ServedModel.from_onnx` — a ``.onnx`` file through the existing
  ONNX importer.

Quantized (int8) models load through the SAME loaders: a
``contrib.quantization.quantize_model`` symbol/params pair (or its
``save_checkpoint`` round trip) is detected by its int8 weight params,
reported as ``weight_dtype: "int8"`` in ``stats()``/``/v1/models``, and
compiled under a token salted with the weight dtype — the int8 bucket
ladder gets its own executables in the persistent disk cache, warming
exactly like the float ladder (zero recompiles under traffic after
``warmup()``; docs/PERFORMANCE.md "Int8 inference").

Bucket ladder note: the default smallest bucket is **2**, not 1 — XLA's
CPU matmul takes a GEMV kernel path at batch 1 whose last-bit rounding
differs from the GEMM path every other bucket takes. With buckets >= 2 a
request's response is **bit-identical** no matter which bucket or
batch-mates it was coalesced with (row-independent kernels; padding
never leaks), which the serving test suite asserts.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as _np

from . import config as _config
from .errors import ModelNotFound

__all__ = ["ServedModel", "ModelContainer"]


def _as_raw(v):
    from ..ndarray import NDArray

    if isinstance(v, NDArray):
        return v._data
    import jax.numpy as jnp

    return jnp.asarray(v)


class ServedModel:
    """One inference model: a compiled pure forward + its device-resident
    parameters + a padded-bucket ladder.

    Requests carry an explicit leading batch dim ``(k,) + example_shape``
    (``k >= 1``); the batcher coalesces rows into the nearest bucket.
    """

    def __init__(self, name, forward, param_raws, aux_raws, example_shape,
                 dtype="float32", buckets=None, weight_dtype=None,
                 param_names=None, aux_names=None):
        from .. import compile as _compile

        self.name = str(name)
        self.example_shape = tuple(int(s) for s in example_shape)
        self.dtype = str(dtype)
        # int8-quantized models keep a float INPUT dtype (activations
        # quantize inside the compiled graph) but carry int8 weights;
        # the distinction rides into stats()//models and the compile
        # token so an int8 ladder never collides with its float twin
        if weight_dtype is None:
            weight_dtype = self.dtype
            for r in param_raws:
                if str(getattr(r, "dtype", "")) == "int8":
                    weight_dtype = "int8"
                    break
        self.weight_dtype = str(weight_dtype)
        if buckets is None:
            buckets = _config.effective()["buckets"]
        self.buckets = _config._coerce("buckets", buckets)
        self._praws = tuple(param_raws)
        self._araws = tuple(aux_raws)
        # the model-bus census surface: param names (when the loader
        # knows them) + the version/pinned-tuple pair behind live weight
        # swaps. _pinned is rebound as ONE tuple — a batch reads it once,
        # so every request in a batch sees exactly one consistent
        # (params, aux, version) triple however often swap_params runs
        self.param_names = list(param_names) if param_names else None
        self.aux_names = list(aux_names) if aux_names else None
        self._version = 0
        self._swaps = 0
        self._pinned = (self._praws, self._araws, 0)
        # donation of the (freshly staged, never reused) input batch is a
        # memory win on accelerators; CPU jaxlib only warns about it, so
        # gate on platform (the compile service additionally strips
        # donation on cpu under a cache dir — see its platform policy)
        donate = ()
        try:
            import jax

            if jax.devices()[0].platform != "cpu":
                donate = (2,)
        except Exception:
            pass
        self._fn = _compile.jit(forward, site="serving",
                                token=self._token(forward),
                                donate_argnums=donate)

    def _token(self, forward):
        base = getattr(forward, "_serving_token", None) or repr(forward)
        blob = "\n".join([str(base), repr(self.example_shape), self.dtype,
                          self.weight_dtype])
        return ("serving", hashlib.sha1(blob.encode()).hexdigest()[:16])

    @property
    def quantized(self):
        """True for an int8-weight (quantized) model."""
        return self.weight_dtype == "int8"

    # ------------------------------------------------------------ shape ---
    @property
    def max_bucket(self):
        return self.buckets[-1]

    def bucket_for(self, rows):
        """Smallest bucket >= rows, or None when rows exceeds the ladder."""
        for b in self.buckets:
            if b >= rows:
                return b
        return None

    def validate(self, arr):
        """Coerce one request payload to ``(k,) + example_shape`` in the
        model dtype; raises ValueError on shape/size mismatch."""
        arr = _np.asarray(arr)
        if arr.shape == self.example_shape:
            arr = arr[None]
        if arr.shape[1:] != self.example_shape:
            raise ValueError(
                f"model {self.name!r} expects rows shaped "
                f"{self.example_shape}, got {arr.shape}")
        if arr.shape[0] < 1:
            raise ValueError(f"model {self.name!r}: empty request")
        if arr.shape[0] > self.max_bucket:
            raise ValueError(
                f"model {self.name!r}: request of {arr.shape[0]} rows "
                f"exceeds the largest bucket {self.max_bucket}; split it "
                "client-side")
        if str(arr.dtype) != self.dtype:
            arr = arr.astype(self.dtype)
        return arr

    # ------------------------------------------------------- live swaps ---
    @property
    def version(self):
        """The model-bus version of the pinned parameters (0 = the
        load-time weights, never swapped)."""
        return self._version

    @property
    def swaps(self):
        """How many times swap_params flipped the pinned weights."""
        return self._swaps

    def pinned(self):
        """The current ``(param_raws, aux_raws, version)`` triple as one
        consistent read (what a batch executes against)."""
        return self._pinned

    def census(self):
        """Per-param ``{name, shape, dtype}`` lists — the shape/dtype
        contract a bus record must match to be applied here."""
        def ents(raws, names):
            return [{"name": names[i] if names else None,
                     "shape": list(r.shape), "dtype": str(r.dtype)}
                    for i, r in enumerate(raws)]
        return {"params": ents(self._praws, self.param_names),
                "aux": ents(self._araws, self.aux_names)}

    def swap_params(self, raws, version, aux_raws=None):
        """Atomically flip the served weights to `raws` (host or device
        arrays in param order), stamping `version`.

        Shapes and dtypes MUST match the live census — that is what
        keeps every compiled bucket executable valid (same avals → the
        in-memory jit cache hits; the swap costs only ``device_put`` of
        the new buffers, ZERO recompiles). The flip itself is one tuple
        rebind: in-flight batches finish on the old weights, the next
        batch runs wholly on the new ones.
        """
        import jax

        cur_p, cur_a, _v = self._pinned

        def staged(news, curs, kind):
            news = tuple(news)
            if len(news) != len(curs):
                raise ValueError(
                    f"model {self.name!r}: swap_params got {len(news)} "
                    f"{kind} arrays, serving {len(curs)}")
            out = []
            for i, (new, cur) in enumerate(zip(news, curs)):
                a = _np.asarray(new) if not hasattr(new, "sharding") \
                    else new
                if tuple(a.shape) != tuple(cur.shape) \
                        or str(a.dtype) != str(cur.dtype):
                    raise ValueError(
                        f"model {self.name!r}: swap_params {kind}[{i}] "
                        f"is {a.shape}/{a.dtype}, serving "
                        f"{cur.shape}/{cur.dtype} — the bus census must "
                        "match (shape-changing updates need a rollout)")
                out.append(jax.device_put(
                    a, getattr(cur, "sharding", None)))
            return tuple(out)

        new_p = staged(raws, cur_p, "param")
        new_a = staged(aux_raws if aux_raws is not None else cur_a,
                       cur_a, "aux")
        self._praws = new_p
        self._araws = new_a
        self._version = int(version)
        self._swaps += 1
        self._pinned = (new_p, new_a, int(version))   # the atomic flip
        return self._pinned

    # -------------------------------------------------------------- run ---
    def run_versioned(self, x, rows=None):
        """:meth:`run`, plus the model version the batch executed under
        — read from the pinned triple ONCE, so the whole batch (and its
        response stamps) is consistent across a concurrent swap."""
        import jax

        praws, araws, version = self._pinned
        out = self._fn(praws, araws, x)
        outs = out if isinstance(out, tuple) else (out,)
        host = jax.device_get(outs)
        n = x.shape[0] if rows is None else rows
        return [_np.asarray(o)[:n] for o in host], version

    def run(self, x, rows=None):
        """Execute the compiled forward on a (padded) batch and return the
        outputs as host numpy arrays, sliced to ``rows``. BLOCKS on the
        device→host copy — the batcher always calls this inside a
        ``watchdog.sync('serving.batch', ...)`` span, so a wedged batch
        surfaces as a StallError + crash bundle, never a hung server."""
        return self.run_versioned(x, rows)[0]

    def warmup(self):
        """Compile (or disk-load) every bucket executable ahead of
        traffic; returns a small report. Combined with
        ``compile.warmup()`` this is the warm-pod start: zero recompiles
        once traffic arrives."""
        import time

        t0 = time.perf_counter()
        for b in self.buckets:
            x = _np.zeros((b,) + self.example_shape, dtype=self.dtype)
            self.run(x, 0)
        return {"buckets": list(self.buckets),
                "ms": round((time.perf_counter() - t0) * 1e3, 1)}

    def __repr__(self):
        return (f"ServedModel({self.name!r}, example={self.example_shape}, "
                f"dtype={self.dtype}, weight_dtype={self.weight_dtype}, "
                f"buckets={self.buckets})")

    # ---------------------------------------------------------- loaders ---
    @classmethod
    def from_block(cls, name, block, example_shape, dtype="float32",
                   buckets=None):
        """Serve a gluon (Hybrid)Block with materialized parameters.
        Parameters are snapshotted at build time (later training does not
        leak into serving)."""
        from .. import autograd
        from ..ndarray import NDArray

        params = block.collect_params()
        handles = []
        for pname, p in params.items():
            if p._data is None:
                raise ValueError(
                    f"model {name!r}: parameter {pname!r} not initialized; "
                    "run one forward pass (or initialize with explicit "
                    "shapes) first")
            handles.append(p.data())

        def fwd(praws, araws, x):
            # the ShardedTrainer.predict idiom: rebind the live handles to
            # the traced values for the duration of the trace
            saved = [(h, h._data) for h in handles]
            try:
                for h, r in zip(handles, praws):
                    h._data = r
                with autograd.pause(train_mode=False):
                    out = block.forward(NDArray(x))
                outs = out if isinstance(out, (tuple, list)) else (out,)
                return tuple(o._data for o in outs)
            finally:
                for h, orig in saved:
                    h._data = orig

        fwd._serving_token = ("block", repr(block), tuple(params))
        # a REAL snapshot, not an alias: a ShardedTrainer over the same
        # block donates its param buffers every step, which would tear
        # the served weights out from under in-flight batches in a
        # train-and-serve process (the model-bus topology).  Round-trip
        # through host so the snapshot also sheds any mesh sharding the
        # trainer put on the source buffers — serving inputs live on the
        # default device, and a committed multi-device parameter would
        # make the jitted forward reject the batch.
        import jax
        import jax.numpy as jnp

        praws = tuple(jnp.asarray(_np.asarray(jax.device_get(h._data)))
                      for h in handles)
        return cls(name, fwd, praws, (), example_shape, dtype, buckets,
                   param_names=list(params))

    @classmethod
    def from_symbol(cls, name, sym, arg_params=None, aux_params=None,
                    input_name=None, example_shape=None, dtype="float32",
                    buckets=None):
        """Serve a Symbol graph + parameter dicts (the MXPred surface)."""
        if example_shape is None:
            raise ValueError("from_symbol requires example_shape (the "
                             "per-row input shape, without the batch dim)")
        arg_params = dict(arg_params or {})
        aux_params = dict(aux_params or {})
        arg_names = list(sym.list_arguments())
        aux_names = list(sym.list_auxiliary_states())
        if input_name is None:
            data_names = [n for n in arg_names if n not in arg_params]
            if len(data_names) != 1:
                raise ValueError(
                    f"model {name!r}: cannot infer the data input from "
                    f"{data_names or arg_names}; pass input_name=")
            input_name = data_names[0]
        elif input_name not in arg_names:
            raise ValueError(f"model {name!r}: {input_name!r} is not an "
                             f"argument of the symbol ({arg_names})")
        pnames = [n for n in arg_names if n != input_name]
        missing = [n for n in pnames if n not in arg_params] + \
                  [n for n in aux_names if n not in aux_params]
        if missing:
            raise ValueError(
                f"model {name!r}: no parameter values for {missing}")
        run = sym._build_eval()

        def fwd(praws, araws, x):
            import jax

            args = dict(zip(pnames, praws))
            args[input_name] = x
            auxs = dict(zip(aux_names, araws))
            # fixed key: inference is deterministic (dropout is identity
            # with training=False; the key is only plumbing)
            outs, _ = run(args, auxs, jax.random.PRNGKey(0), False)
            return tuple(outs)

        fwd._serving_token = ("symbol",
                              hashlib.sha1(
                                  sym.tojson().encode()).hexdigest()[:16],
                              input_name, tuple(pnames))
        praws = tuple(_as_raw(arg_params[n]) for n in pnames)
        araws = tuple(_as_raw(aux_params[n]) for n in aux_names)
        return cls(name, fwd, praws, araws, example_shape, dtype, buckets,
                   param_names=pnames, aux_names=aux_names)

    @classmethod
    def from_checkpoint(cls, name, prefix, epoch, example_shape,
                        dtype="float32", buckets=None, input_name=None):
        """Serve a ``save_checkpoint`` pair (symbol json + params)."""
        from ..model import load_checkpoint

        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls.from_symbol(name, sym, arg_params, aux_params,
                               input_name=input_name,
                               example_shape=example_shape, dtype=dtype,
                               buckets=buckets)

    @classmethod
    def from_onnx(cls, name, model_file, example_shape, dtype="float32",
                  buckets=None, input_name=None):
        """Serve a ``.onnx`` file through the existing ONNX importer."""
        from ..onnx.onnx2mx import import_model

        sym, arg_params, aux_params = import_model(model_file)
        return cls.from_symbol(name, sym, arg_params, aux_params,
                               input_name=input_name,
                               example_shape=example_shape, dtype=dtype,
                               buckets=buckets)


class ModelContainer:
    """An ordered, named set of :class:`ServedModel`\\ s — what a
    :class:`~mxnet_tpu.serving.server.ModelServer` serves."""

    def __init__(self, models=None):
        self._models = OrderedDict()
        for m in models or ():
            self.add(m)

    def add(self, model: ServedModel) -> ServedModel:
        if model.name in self._models:
            raise ValueError(f"model {model.name!r} already in container")
        self._models[model.name] = model
        return model

    # convenience constructors mirroring the ServedModel loaders
    def add_block(self, name, block, example_shape, **kw):
        return self.add(ServedModel.from_block(name, block, example_shape,
                                               **kw))

    def add_symbol(self, name, sym, arg_params=None, aux_params=None, **kw):
        return self.add(ServedModel.from_symbol(name, sym, arg_params,
                                                aux_params, **kw))

    def add_checkpoint(self, name, prefix, epoch, example_shape, **kw):
        return self.add(ServedModel.from_checkpoint(name, prefix, epoch,
                                                    example_shape, **kw))

    def add_onnx(self, name, model_file, example_shape, **kw):
        return self.add(ServedModel.from_onnx(name, model_file,
                                              example_shape, **kw))

    def names(self):
        return list(self._models)

    def get(self, name) -> ServedModel:
        m = self._models.get(name)
        if m is None:
            raise ModelNotFound(
                f"model {name!r} not in container; available: "
                f"{sorted(self._models)}")
        return m

    def __getitem__(self, name):
        return self.get(name)

    def __contains__(self, name):
        return name in self._models

    def __iter__(self):
        return iter(self._models.values())

    def __len__(self):
        return len(self._models)

    def warmup(self):
        """Warm-pod start: replay the compile service's warmup manifest
        (disk-cache loads for every previously-seen signature), then walk
        every model's bucket ladder. After this, steady-state traffic
        shows only cache hits in ``compile.stats()``."""
        from .. import compile as _compile

        report = {"service": _compile.warmup(), "models": {}}
        for m in self:
            report["models"][m.name] = m.warmup()
        return report
