"""Serving knobs: defaults + the ``MXNET_TPU_SERVING`` env grammar.

Mirrors the ``MXNET_TPU_FAULTS`` / ``MXNET_TPU_WATCHDOG`` convention: one
environment variable, read once at first use (so subprocesses inherit a
configuration), overridable programmatically via :func:`configure`.
Entries are separated by ``,`` or ``;``::

    buckets:<b1|b2|...>   padded batch buckets compiled per model
                          (default 2|4|8|16|32 — the smallest bucket is 2
                          so every request takes XLA's GEMM kernel path;
                          a 1-row bucket takes the GEMV path whose
                          last-bit rounding differs, breaking the
                          bit-identical-across-batch-mates guarantee)
    max_queue:<N>         admission bound: rows waiting per model before
                          submit() fast-rejects with ServerBusyError
                          (default 1024)
    max_wait_ms:<F>       continuous-batching coalescing window: how long
                          the collector holds an underfull batch waiting
                          for batch-mates (default 2.0)
    timeout_ms:<F>        default ServingFuture.result() deadline — every
                          client wait is bounded (default 30000)
    stage:<0|1>           device-put staging thread (h2d overlaps the
                          in-flight compiled batch; default 1)
    cache:<0|1>           content-addressed prediction cache in front of
                          the batcher (key = model-version x input bytes,
                          invalidated when the served version flips;
                          default 0 — enable for hot-key traffic)
    cache_entries:<N>     bounded LRU capacity of the prediction cache
                          per model (default 4096)

Examples::

    MXNET_TPU_SERVING="buckets:2|8|32,max_wait_ms:5"
    serving.configure({"max_queue": 64}, max_wait_ms=1.0)
"""
from __future__ import annotations

import os
import re
import threading

__all__ = ["configure", "configure_from_env", "effective", "describe",
           "DEFAULTS"]

ENV = "MXNET_TPU_SERVING"

DEFAULTS = {
    "buckets": (2, 4, 8, 16, 32),
    "max_queue": 1024,
    "max_wait_ms": 2.0,
    "timeout_ms": 30000.0,
    "stage": True,
    "cache": False,
    "cache_entries": 4096,
}

_lock = threading.Lock()
_CFG: dict | None = None
_loaded_env = False


def _parse_buckets(val):
    try:
        buckets = tuple(sorted({int(b) for b in val.split("|") if b.strip()}))
    except ValueError:
        raise ValueError(f"bad serving buckets {val!r}: expected "
                         "'|'-separated integers, e.g. buckets:2|4|8")
    if not buckets or any(b < 1 for b in buckets):
        raise ValueError(f"bad serving buckets {val!r}: need at least one "
                         "positive batch size")
    return buckets


def _coerce(key, val):
    if key == "buckets":
        if isinstance(val, str):
            return _parse_buckets(val)
        buckets = tuple(sorted({int(b) for b in val}))
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"bad serving buckets {val!r}")
        return buckets
    if key in ("max_queue", "cache_entries"):
        n = int(val)
        if n < 1:
            raise ValueError(f"serving {key} must be >= 1, got {n}")
        return n
    if key in ("max_wait_ms", "timeout_ms"):
        f = float(val)
        if f < 0:
            raise ValueError(f"serving {key} must be >= 0, got {f}")
        return f
    if key in ("stage", "cache"):
        if isinstance(val, str):
            return val.strip().lower() not in ("0", "false", "off", "no")
        return bool(val)
    raise ValueError(
        f"unknown serving option {key!r}; expected one of {sorted(DEFAULTS)}")


def _parse(spec):
    cfg = dict(DEFAULTS)
    for entry in re.split(r"[;,]", spec):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, val = entry.partition(":")
        key, val = key.strip(), val.strip()
        if not sep or not val:
            raise ValueError(
                f"bad {ENV} entry {entry!r}: expected <option>:<value>")
        cfg[key] = _coerce(key, val)
    return cfg


def configure(spec=None, **options):
    """Install a serving configuration (replacing any previous one).

    spec : str in the grammar above, dict ``{option: value}``, or None to
        fall back to the defaults. ``options`` keyword overrides apply on
        top. Pass nothing at all to reset to defaults/env precedence.
    """
    global _CFG, _loaded_env
    if isinstance(spec, dict):
        cfg = dict(DEFAULTS)
        for k, v in spec.items():
            cfg[k] = _coerce(k, v)
    elif spec:
        cfg = _parse(spec)
    else:
        cfg = dict(DEFAULTS)
    for k, v in options.items():
        cfg[k] = _coerce(k, v)
    with _lock:
        _loaded_env = True  # explicit configure overrides the env
        _CFG = cfg
    return dict(cfg)


def configure_from_env(force=True):
    """(Re-)read ``MXNET_TPU_SERVING`` — tests use it to restore the
    ambient configuration after exercising explicit ones."""
    global _loaded_env, _CFG
    if force:
        with _lock:
            _loaded_env = False
            _CFG = None
    _ensure_env()


def _ensure_env():
    global _loaded_env, _CFG
    if _loaded_env:
        return
    with _lock:
        if _loaded_env:
            return
        _loaded_env = True
        env = os.environ.get(ENV, "")
        if env:
            try:
                _CFG = _parse(env)
            except ValueError as e:
                from .. import log as _log

                _log.get_logger("mxnet_tpu.serving").warning(
                    "ignoring invalid %s: %s", ENV, e)
                _CFG = None


def effective() -> dict:
    """The effective configuration dict (env-seeded, configure-overridden)."""
    _ensure_env()
    cfg = _CFG
    return dict(cfg) if cfg is not None else dict(DEFAULTS)


def describe() -> dict:
    """Knobs + provenance for ``tools/diagnose.py``."""
    out = effective()
    out["env"] = os.environ.get(ENV, "<unset>")
    return out
