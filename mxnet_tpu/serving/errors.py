"""Serving error taxonomy — every failure a client can see, typed.

The shape mirrors an HTTP predict front end (the codes the http module
maps them to): admission rejects are *fast* (429/503 analogues raised at
``submit`` time, never after queueing), execution failures carry their
cause, and every client wait is deadline-bounded (:class:`RequestTimeout`
instead of a hung caller).
"""
from __future__ import annotations

__all__ = ["ServingError", "ModelNotFound", "ServerBusyError",
           "ServerDrainingError", "RequestError", "RequestTimeout",
           "DeadlineExceeded"]


class ServingError(RuntimeError):
    """Base class for every serving-layer error."""


class ModelNotFound(ServingError):
    """The named model is not in the served container (HTTP 404)."""


class ServerBusyError(ServingError):
    """Admission control fast-reject: the model's queue-depth bound is
    full (HTTP 429). Raised AT submit time — an overloaded server sheds
    load immediately instead of growing an unbounded queue whose tail
    latency nobody can meet. Attributes: ``model``, ``depth`` (rows
    waiting), ``limit``."""

    def __init__(self, model, depth, limit):
        self.model = model
        self.depth = depth
        self.limit = limit
        super().__init__(
            f"model {model!r} queue is full ({depth}/{limit} rows waiting)"
            " — retry with backoff (HTTP 429 analogue)")


class ServerDrainingError(ServerBusyError):
    """Admission stopped: the server is draining for shutdown/preemption
    (HTTP 503). In-flight and queued requests still complete; new ones
    must go to another replica."""

    def __init__(self, model, reason="draining"):
        self.model = model
        self.depth = 0
        self.limit = 0
        ServingError.__init__(
            self, f"model {model!r} not admitting requests ({reason}) — "
            "the server is shutting down; retry against another replica")


class RequestError(ServingError):
    """The batch this request was coalesced into failed (injected fault,
    watchdog StallError, bad input discovered at execution). The
    underlying exception is ``cause`` (and ``__cause__``)."""

    def __init__(self, message, cause=None):
        self.cause = cause
        super().__init__(message)
        if cause is not None:
            self.__cause__ = cause


class RequestTimeout(ServingError):
    """ServingFuture.result() deadline expired before the response
    arrived. The request may still complete server-side; the client-side
    wait is bounded by construction."""


class DeadlineExceeded(ServingError):
    """The request's *own* deadline cannot be met, so it was dropped
    BEFORE consuming a batch slot (HTTP 504 analogue, but cheap: no
    compute was wasted on an answer nobody is waiting for). Raised at
    submit time when the estimated batch latency already overshoots the
    deadline, or by the collector when the deadline expired while the
    request sat in the queue. Attributes: ``model``, ``deadline_ms``,
    ``estimate_ms`` (what the batcher thought it would take, when
    known), ``where`` (``"submit"`` | ``"queue"``)."""

    def __init__(self, model, deadline_ms, estimate_ms=None, where="queue"):
        self.model = model
        self.deadline_ms = deadline_ms
        self.estimate_ms = estimate_ms
        self.where = where
        est = (f"; estimated completion {estimate_ms:.1f}ms"
               if estimate_ms is not None else "")
        super().__init__(
            f"model {model!r} request dropped at {where}: cannot meet "
            f"{deadline_ms:.1f}ms deadline{est} (HTTP 504 analogue, "
            "no batch slot was consumed)")
