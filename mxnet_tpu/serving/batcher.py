"""Continuous/dynamic batching: per-model request queue → padded buckets.

One :class:`BucketBatcher` per served model, two daemon threads:

* the **collector** pops waiting requests, coalesces them into the
  nearest padded bucket under the ``max_wait_ms`` admission deadline
  (an underfull batch launches as soon as the oldest request has waited
  the window; a full bucket launches immediately), pads with zero rows,
  and **stages** the batch onto the device through the shared
  :class:`~mxnet_tpu.io.io.DeviceStager` (the PrefetchingIter
  device-put stage) — so h2d for batch N+1 overlaps the compiled call
  for batch N;
* the **runner** executes each staged batch under a
  ``watchdog.sync("serving.batch", ...)`` deadline with the
  ``serving.batch`` fault-injection point inside the span, slices the
  outputs back per request, and fulfills the futures.

Continuous: the collector never waits for the runner — requests arriving
while a batch executes coalesce into the next one, so batches grow with
load (high fill ratio under pressure, low latency when idle).

Admission control: ``submit`` fast-rejects with
:class:`~mxnet_tpu.serving.errors.ServerBusyError` the moment the
queue-depth bound is hit (429 semantics — shed load, don't queue
unboundedly) and with :class:`ServerDrainingError` once a drain started.

Tracing: when :mod:`mxnet_tpu.telemetry.trace` is on, every request
carries a :class:`~mxnet_tpu.telemetry.trace.RequestTrace` on its
future — the collector/runner stamp pipeline marks (popped, padded,
staged, compiled-call begin/end) and fulfilment commits the five-phase
queue_wait / batch_collect / h2d / compute / respond breakdown
(``ServingFuture.breakdown()``; docs/OBSERVABILITY.md "Tracing").

Robustness: a hung batch (wedged device, poisoned input) blows its
watchdog deadline → crash bundle + StallError; the batch's requests fail
with a :class:`RequestError` carrying the cause and the batcher KEEPS
SERVING the next batch. Nothing in this module blocks unboundedly —
every wait carries a timeout (the ``serving-blocking-call`` mxlint rule
gates this contract).
"""
from __future__ import annotations

import queue as _qmod
import threading
import time
from collections import deque

import numpy as _np

from . import config as _config
from ..telemetry import trace as _trace
from .errors import (RequestError, RequestTimeout, ServerBusyError,
                     ServerDrainingError)
from .metrics import ModelMetrics

__all__ = ["ServingFuture", "BucketBatcher"]


class ServingFuture:
    """Client handle for one in-flight request. ``result`` is ALWAYS
    deadline-bounded: with no explicit timeout the configured
    ``timeout_ms`` default applies."""

    __slots__ = ("model", "t_submit", "t_done", "_event", "_result",
                 "_error", "_trace", "model_version")

    def __init__(self, model):
        self.model = model
        self.t_submit = time.monotonic()
        self.t_done = None
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._trace = None
        # the model-bus version the answering batch executed under
        # (stamped at fulfilment; None until then / on failure)
        self.model_version = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The response (one numpy array, or a list for multi-output
        models), or raises the request's failure. Bounded: raises
        :class:`RequestTimeout` after ``timeout`` seconds (default: the
        configured ``timeout_ms``)."""
        if timeout is None:
            timeout = _config.effective()["timeout_ms"] / 1e3
        if not self._event.wait(timeout):
            raise RequestTimeout(
                f"request to {self.model!r} not answered within "
                f"{timeout:g}s")
        if self._error is not None:
            raise self._error
        return self._result

    def latency_ms(self):
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3

    @property
    def request_id(self):
        """The propagated trace/request id (None with tracing off)."""
        return self._trace.request_id if self._trace is not None else None

    def breakdown(self):
        """The five-phase per-request breakdown (queue_wait /
        batch_collect / h2d / compute / respond, milliseconds) once the
        request finished — None before completion or with tracing off."""
        return self._trace.breakdown if self._trace is not None else None

    def _fulfill(self, result):
        self.t_done = time.monotonic()
        self._result = result
        self._event.set()

    def _fail(self, error):
        self.t_done = time.monotonic()
        self._error = error
        self._event.set()


class _Request:
    __slots__ = ("arr", "n", "fut")

    def __init__(self, arr, n, fut):
        self.arr = arr
        self.n = n
        self.fut = fut


class BucketBatcher:
    """The per-model queue + continuous-batching worker pair."""

    def __init__(self, model, metrics=None, max_queue=None,
                 max_wait_ms=None, stage=None):
        cfg = _config.effective()
        self.model = model
        self.metrics = metrics or ModelMetrics(model.name)
        self._max_queue = int(cfg["max_queue"] if max_queue is None
                              else max_queue)
        self._max_wait = (cfg["max_wait_ms"] if max_wait_ms is None
                          else float(max_wait_ms)) / 1e3
        self._queue = deque()
        self._rows = 0           # rows waiting (the admission bound)
        self._inflight = 0       # batches popped but not yet finished
        self._cond = threading.Condition()
        self._staged = _qmod.Queue(maxsize=1)
        self._draining = False
        self._stopping = False
        self._threads = ()
        do_stage = cfg["stage"] if stage is None else bool(stage)
        self._stager = None
        if do_stage:
            try:
                import jax

                from ..io.io import DeviceStager

                self._stager = DeviceStager(device=jax.devices()[0])
            except Exception:
                self._stager = None

    # ----------------------------------------------------------- control --
    def start(self):
        if self._threads:
            return self
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"mxtpu-serve-{self.model.name}-collect")
        self._runner = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"mxtpu-serve-{self.model.name}-run")
        self._threads = (self._collector, self._runner)
        self._collector.start()
        self._runner.start()
        return self

    def queue_depth(self):
        """Rows waiting for a batch (the bound admission checks)."""
        return self._rows

    def ladder_census(self):
        """The bucket ladder with its observed batch counts and the
        model's dtypes — the int8-serving proof surface (diagnose's
        Quantization report, chaos phase 12): every ladder bucket that
        warmed must still be servable after a fault."""
        with self.metrics._lock:
            census = dict(sorted(self.metrics.bucket_census.items()))
        return {"buckets": list(self.model.buckets),
                "bucket_census": census,
                "dtype": self.model.dtype,
                "weight_dtype": self.model.weight_dtype}

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=30.0):
        """Stop admission, answer everything already admitted (queued AND
        in flight). Returns True when fully drained within `timeout`."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._cond:
                if not self._queue and self._inflight == 0:
                    return True
            time.sleep(0.005)
        return False

    def stop(self, timeout=5.0):
        """Stop the worker threads; queued-but-unanswered requests fail
        with ServerDrainingError (call :meth:`drain` first for a graceful
        shutdown that answers them)."""
        with self._cond:
            self._stopping = True
            self._draining = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = ()
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._rows = 0
        for r in leftovers:
            r.fut._fail(ServerDrainingError(self.model.name, "stopped"))
            self.metrics.record_fail()

    # ------------------------------------------------------------ submit --
    def submit(self, arr):
        """Admit one request (fast-reject on a full queue or a draining
        server) and return its :class:`ServingFuture`."""
        arr = self.model.validate(arr)
        n = arr.shape[0]
        fut = ServingFuture(self.model.name)
        if _trace.enabled():
            # propagated context: the HTTP front end binds X-Request-Id
            # on this thread; in-process callers get a fresh id
            fut._trace = _trace.request_begin(self.model.name, rows=n)
        with self._cond:
            if self._draining or self._stopping:
                self.metrics.record_reject()
                raise ServerDrainingError(self.model.name)
            if self._rows + n > self._max_queue:
                self.metrics.record_reject()
                raise ServerBusyError(self.model.name, self._rows,
                                      self._max_queue)
            self._queue.append(_Request(arr, n, fut))
            self._rows += n
            self._cond.notify_all()
        self.metrics.record_submit()
        return fut

    # --------------------------------------------------------- collector --
    def _collect(self):
        """Pop one coalesced batch (requests, rows) under the admission
        deadline, or None when stopping."""
        with self._cond:
            while True:
                while not self._queue:
                    if self._stopping:
                        return None
                    self._cond.wait(timeout=0.1)
                cap = self.model.max_bucket
                deadline = self._queue[0].fut.t_submit + self._max_wait
                while (self._queue and self._rows < cap
                       and not self._stopping and not self._draining):
                    now = time.monotonic()
                    if now >= deadline:
                        break
                    self._cond.wait(timeout=min(deadline - now, 0.05))
                if self._queue:
                    break  # else: raced with stop()'s clear; re-wait
            reqs, rows = [], 0
            while self._queue and rows + self._queue[0].n <= cap:
                r = self._queue.popleft()
                reqs.append(r)
                rows += r.n
            self._rows -= rows
            self._inflight += 1
            t_pop = time.monotonic()
            for r in reqs:   # queue_wait ends here for the whole batch
                if r.fut._trace is not None:
                    r.fut._trace.mark("collected", t_pop)
            return reqs, rows

    def _pad(self, reqs, rows, bucket):
        shape = (bucket,) + self.model.example_shape
        out = _np.zeros(shape, dtype=self.model.dtype)
        off = 0
        for r in reqs:
            out[off:off + r.n] = r.arr
            off += r.n
        return out

    def _collect_loop(self):
        while True:
            batch = self._collect()
            if batch is None:
                return
            reqs, rows = batch
            bucket = self.model.bucket_for(rows)
            x = self._pad(reqs, rows, bucket)
            t_pad = time.monotonic()
            if self._stager is not None:
                # h2d on this thread overlaps the runner's compiled call
                try:
                    x = self._stager.put(x)
                except Exception:
                    pass  # staging is an optimisation; jit transfers too
            t_staged = time.monotonic()
            for r in reqs:   # batch_collect = pad; h2d = the staged put
                if r.fut._trace is not None:
                    r.fut._trace.mark("assembled", t_pad)
                    r.fut._trace.mark("staged", t_staged)
            while True:
                try:
                    self._staged.put((reqs, x, rows, bucket), timeout=0.25)
                    break
                except _qmod.Full:
                    if self._stopping:
                        self._fail_batch(reqs, ServerDrainingError(
                            self.model.name, "stopped"))
                        return

    # ------------------------------------------------------------ runner --
    def _fail_batch(self, reqs, err):
        for r in reqs:
            r.fut._fail(err)
            if r.fut._trace is not None:
                r.fut._trace.finish(error=type(err).__name__)
        self.metrics.record_fail(len(reqs))
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def _run_loop(self):
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        model = self.model
        while True:
            try:
                item = self._staged.get(timeout=0.25)
            except _qmod.Empty:
                if self._stopping and not self._collector.is_alive():
                    return
                continue
            reqs, x, rows, bucket = item

            def run():
                # 'serving.batch' injection: raise = failed batch, hang =
                # the wedged-device scenario the watchdog converts into a
                # crash bundle + StallError, preempt = SIGTERM mid-load
                _faults.point("serving.batch")
                return model.run_versioned(x, rows)

            t0 = time.monotonic()
            for r in reqs:
                if r.fut._trace is not None:
                    r.fut._trace.mark("run_begin", t0)
            try:
                outs, model_version = _watchdog.sync(
                    "serving.batch", run,
                    label=f"{model.name} bucket={bucket} rows={rows}")
            except BaseException as e:
                if isinstance(e, _watchdog.StallError):
                    self.metrics.record_stall()
                self._fail_batch(reqs, RequestError(
                    f"model {model.name!r}: batch of {rows} rows failed: "
                    f"{type(e).__name__}: {e}", cause=e))
                continue
            t_run_end = time.monotonic()
            dur_ms = (t_run_end - t0) * 1e3
            off = 0
            now = t_run_end
            for r in reqs:
                sliced = [o[off:off + r.n] for o in outs]
                if r.fut._trace is not None:
                    r.fut._trace.mark("run_end", t_run_end)
                r.fut.model_version = model_version
                r.fut._fulfill(sliced[0] if len(sliced) == 1 else sliced)
                if r.fut._trace is not None:
                    r.fut._trace.finish(bucket=bucket)
                off += r.n
                self.metrics.record_complete((now - r.fut.t_submit) * 1e3)
            self.metrics.record_batch(bucket, rows, dur_ms,
                                      self.queue_depth())
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()
