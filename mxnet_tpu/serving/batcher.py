"""Continuous/dynamic batching: per-model request queue → padded buckets.

One :class:`BucketBatcher` per served model, two daemon threads:

* the **collector** pops waiting requests, coalesces them into the
  nearest padded bucket under the ``max_wait_ms`` admission deadline
  (an underfull batch launches as soon as the oldest request has waited
  the window; a full bucket launches immediately), pads with zero rows,
  and **stages** the batch onto the device through the shared
  :class:`~mxnet_tpu.io.io.DeviceStager` (the PrefetchingIter
  device-put stage) — so h2d for batch N+1 overlaps the compiled call
  for batch N;
* the **runner** executes each staged batch under a
  ``watchdog.sync("serving.batch", ...)`` deadline with the
  ``serving.batch`` fault-injection point inside the span, slices the
  outputs back per request, and fulfills the futures.

Continuous: the collector never waits for the runner — requests arriving
while a batch executes coalesce into the next one, so batches grow with
load (high fill ratio under pressure, low latency when idle).

Admission control: ``submit`` fast-rejects with
:class:`~mxnet_tpu.serving.errors.ServerBusyError` the moment the
queue-depth bound is hit (429 semantics — shed load, don't queue
unboundedly) and with :class:`ServerDrainingError` once a drain started.

QoS + deadlines: requests carry a **priority class** (``interactive`` /
``batch``) and an optional **deadline**. The collector always drains
interactive requests first and lets batch traffic fill the leftover
bucket capacity, so under overload batch starves before interactive p99
degrades; the admission bound is likewise partitioned (batch rows count
against the whole queue bound, interactive admission ignores the batch
backlog). Deadline-carrying requests that *provably* cannot meet their
deadline are dropped with :class:`DeadlineExceeded` BEFORE consuming a
batch slot — at submit time when the measured batch-execution estimate
already overshoots, and again at collect time when the deadline expired
(or the estimate overshoots) while the request waited.

Prediction cache: with ``serving.config`` ``cache:1`` a
content-addressed :class:`~mxnet_tpu.serving.cache.PredictionCache`
(key = model name x served version x input bytes) sits in front of
admission — a hit fulfils the future on the submit thread without
touching the queue or the device, and content-identical requests whose
leader is already queued/in flight attach as **followers** fulfilled by
the leader's batch (so a duplicated request — a hedge landing on the
same worker, a retry — never double-runs a donating batch). Entries are
only inserted when the executing version matches the version the key
was built under, so a model-bus version flip can never serve stale
predictions: the old generation's keys simply stop being generated.

Tracing: when :mod:`mxnet_tpu.telemetry.trace` is on, every request
carries a :class:`~mxnet_tpu.telemetry.trace.RequestTrace` on its
future — the collector/runner stamp pipeline marks (popped, padded,
staged, compiled-call begin/end) and fulfilment commits the five-phase
queue_wait / batch_collect / h2d / compute / respond breakdown
(``ServingFuture.breakdown()``; docs/OBSERVABILITY.md "Tracing").

Robustness: a hung batch (wedged device, poisoned input) blows its
watchdog deadline → crash bundle + StallError; the batch's requests fail
with a :class:`RequestError` carrying the cause and the batcher KEEPS
SERVING the next batch. Nothing in this module blocks unboundedly —
every wait carries a timeout (the ``serving-blocking-call`` mxlint rule
gates this contract).
"""
from __future__ import annotations

import queue as _qmod
import threading
import time
from collections import deque

import numpy as _np

from . import config as _config
from . import cache as _pcache
from ..telemetry import trace as _trace
from .errors import (DeadlineExceeded, RequestError, RequestTimeout,
                     ServerBusyError, ServerDrainingError)
from .metrics import ModelMetrics

__all__ = ["ServingFuture", "BucketBatcher", "PRIORITIES"]

PRIORITIES = ("interactive", "batch")


class ServingFuture:
    """Client handle for one in-flight request. ``result`` is ALWAYS
    deadline-bounded: with no explicit timeout the configured
    ``timeout_ms`` default applies."""

    __slots__ = ("model", "t_submit", "t_done", "_event", "_result",
                 "_error", "_trace", "model_version", "priority",
                 "deadline_ms", "cache_hit")

    def __init__(self, model, priority="interactive", deadline_ms=None):
        self.model = model
        self.t_submit = time.monotonic()
        self.t_done = None
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._trace = None
        # the model-bus version the answering batch executed under
        # (stamped at fulfilment; None until then / on failure)
        self.model_version = None
        self.priority = priority
        self.deadline_ms = deadline_ms
        self.cache_hit = False   # answered from the prediction cache

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The response (one numpy array, or a list for multi-output
        models), or raises the request's failure. Bounded: raises
        :class:`RequestTimeout` after ``timeout`` seconds (default: the
        configured ``timeout_ms``)."""
        if timeout is None:
            timeout = _config.effective()["timeout_ms"] / 1e3
        if not self._event.wait(timeout):
            raise RequestTimeout(
                f"request to {self.model!r} not answered within "
                f"{timeout:g}s")
        if self._error is not None:
            raise self._error
        return self._result

    def latency_ms(self):
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3

    @property
    def request_id(self):
        """The propagated trace/request id (None with tracing off)."""
        return self._trace.request_id if self._trace is not None else None

    def breakdown(self):
        """The five-phase per-request breakdown (queue_wait /
        batch_collect / h2d / compute / respond, milliseconds) once the
        request finished — None before completion or with tracing off."""
        return self._trace.breakdown if self._trace is not None else None

    def _fulfill(self, result):
        self.t_done = time.monotonic()
        self._result = result
        self._event.set()

    def _fail(self, error):
        self.t_done = time.monotonic()
        self._error = error
        self._event.set()


class _Request:
    __slots__ = ("arr", "n", "fut", "deadline", "key", "key_version",
                 "followers")

    def __init__(self, arr, n, fut, deadline=None, key=None,
                 key_version=None):
        self.arr = arr
        self.n = n
        self.fut = fut
        self.deadline = deadline       # absolute monotonic, or None
        self.key = key                 # prediction-cache content key
        self.key_version = key_version  # served version the key names
        self.followers = []            # deduped futures riding this one


class BucketBatcher:
    """The per-model queue + continuous-batching worker pair."""

    def __init__(self, model, metrics=None, max_queue=None,
                 max_wait_ms=None, stage=None, cache=None,
                 cache_entries=None):
        cfg = _config.effective()
        self.model = model
        self.metrics = metrics or ModelMetrics(model.name)
        self._max_queue = int(cfg["max_queue"] if max_queue is None
                              else max_queue)
        self._max_wait = (cfg["max_wait_ms"] if max_wait_ms is None
                          else float(max_wait_ms)) / 1e3
        self._qi = deque()       # interactive: always drained first
        self._qb = deque()       # batch: fills leftover bucket capacity
        self._rows = 0           # total rows waiting (the batch bound)
        self._rows_i = 0         # interactive rows waiting (its own bound)
        self._inflight = 0       # batches popped but not yet finished
        self._cond = threading.Condition()
        self._leaders = {}       # content key -> queued/in-flight _Request
        self._est_ms = None      # EWMA batch-execution estimate
        use_cache = cfg["cache"] if cache is None else bool(cache)
        self.cache = _pcache.PredictionCache(
            cfg["cache_entries"] if cache_entries is None
            else cache_entries) if use_cache else None
        self._staged = _qmod.Queue(maxsize=1)
        self._draining = False
        self._stopping = False
        self._threads = ()
        do_stage = cfg["stage"] if stage is None else bool(stage)
        self._stager = None
        if do_stage:
            try:
                import jax

                from ..io.io import DeviceStager

                self._stager = DeviceStager(device=jax.devices()[0])
            except Exception:
                self._stager = None

    # ----------------------------------------------------------- control --
    def start(self):
        if self._threads:
            return self
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"mxtpu-serve-{self.model.name}-collect")
        self._runner = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"mxtpu-serve-{self.model.name}-run")
        self._threads = (self._collector, self._runner)
        self._collector.start()
        self._runner.start()
        return self

    def queue_depth(self):
        """Rows waiting for a batch (the bound admission checks)."""
        return self._rows

    def ladder_census(self):
        """The bucket ladder with its observed batch counts and the
        model's dtypes — the int8-serving proof surface (diagnose's
        Quantization report, chaos phase 12): every ladder bucket that
        warmed must still be servable after a fault."""
        with self.metrics._lock:
            census = dict(sorted(self.metrics.bucket_census.items()))
        return {"buckets": list(self.model.buckets),
                "bucket_census": census,
                "dtype": self.model.dtype,
                "weight_dtype": self.model.weight_dtype}

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=30.0):
        """Stop admission, answer everything already admitted (queued AND
        in flight). Returns True when fully drained within `timeout`."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._cond:
                if not self._qi and not self._qb and self._inflight == 0:
                    return True
            time.sleep(0.005)
        return False

    def stop(self, timeout=5.0):
        """Stop the worker threads; queued-but-unanswered requests fail
        with ServerDrainingError (call :meth:`drain` first for a graceful
        shutdown that answers them)."""
        with self._cond:
            self._stopping = True
            self._draining = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = ()
        with self._cond:
            leftovers = list(self._qi) + list(self._qb)
            self._qi.clear()
            self._qb.clear()
            self._rows = 0
            self._rows_i = 0
            self._leaders.clear()
        for r in leftovers:
            err = ServerDrainingError(self.model.name, "stopped")
            for fut in (r.fut, *r.followers):
                fut._fail(err)
                self.metrics.record_fail()

    # ------------------------------------------------------------ submit --
    def submit(self, arr, priority="interactive", deadline_ms=None):
        """Admit one request (fast-reject on a full queue, a draining
        server, or a provably unmeetable deadline) and return its
        :class:`ServingFuture`. ``priority`` picks the QoS class
        (interactive is drained first; batch fills leftover capacity and
        is the first to starve under overload); ``deadline_ms`` bounds
        how stale an answer is still useful — a request that cannot meet
        it is dropped before consuming a batch slot."""
        arr = self.model.validate(arr)
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r}: expected "
                             f"one of {PRIORITIES}")
        n = arr.shape[0]
        deadline_ms = None if deadline_ms is None else float(deadline_ms)
        fut = ServingFuture(self.model.name, priority=priority,
                            deadline_ms=deadline_ms)
        if _trace.enabled():
            # propagated context: the HTTP front end binds X-Request-Id
            # on this thread; in-process callers get a fresh id
            fut._trace = _trace.request_begin(self.model.name, rows=n)
        deadline = (fut.t_submit + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        key = key_version = None
        if self.cache is not None:
            key_version = self.model.version
            self.cache.observe_version(key_version)
            key = _pcache.content_key(self.model.name, key_version, arr)
            hit = self.cache.get(key)
            self.metrics.record_cache(hit is not None)
            if hit is not None:
                # hit path: fulfilled on the submit thread, no queue, no
                # device — this is the >=10x-faster-than-compute path
                self.metrics.record_submit()
                fut.cache_hit = True
                fut.model_version = key_version
                fut._fulfill(hit)
                if fut._trace is not None:
                    fut._trace.finish()
                self.metrics.record_complete(fut.latency_ms(), priority)
                if deadline_ms is not None:
                    self.metrics.record_deadline_outcome(True)
                return fut
        if deadline_ms is not None and self._est_ms is not None \
                and deadline_ms < self._est_ms:
            # provably doomed: even dispatched immediately, the measured
            # batch execution alone overshoots the deadline
            self.metrics.record_deadline_drop("submit")
            raise DeadlineExceeded(self.model.name, deadline_ms,
                                   self._est_ms, where="submit")
        with self._cond:
            if self._draining or self._stopping:
                self.metrics.record_reject()
                raise ServerDrainingError(self.model.name)
            if key is not None:
                leader = self._leaders.get(key)
                if leader is not None:
                    # content-identical request already queued/in flight:
                    # ride the donating batch instead of re-running it
                    leader.followers.append(fut)
                    self.metrics.record_coalesced()
                    self.metrics.record_submit()
                    return fut
            bound_rows = self._rows_i if priority == "interactive" \
                else self._rows
            if bound_rows + n > self._max_queue:
                self.metrics.record_reject()
                raise ServerBusyError(self.model.name, bound_rows,
                                      self._max_queue)
            req = _Request(arr, n, fut, deadline=deadline, key=key,
                           key_version=key_version)
            if priority == "interactive":
                self._qi.append(req)
                self._rows_i += n
            else:
                self._qb.append(req)
            self._rows += n
            if key is not None:
                self._leaders[key] = req
            self._cond.notify_all()
        self.metrics.record_submit()
        return fut

    # --------------------------------------------------------- collector --
    def _doomed(self, r, now):
        """True when `r` provably cannot meet its deadline: it already
        expired, or the measured batch-execution estimate overshoots the
        time it has left. Checked at pop time, BEFORE a batch slot."""
        if r.deadline is None:
            return False
        if now >= r.deadline:
            return True
        return (self._est_ms is not None
                and now + self._est_ms / 1e3 > r.deadline)

    def _drop_doomed_locked(self, r):
        """Fail one popped-but-doomed request (and its followers) with
        DeadlineExceeded — its rows were already uncounted by the pop,
        so no batch slot is consumed. _cond held."""
        if r.key is not None and self._leaders.get(r.key) is r:
            del self._leaders[r.key]
        err = DeadlineExceeded(self.model.name, r.fut.deadline_ms,
                               self._est_ms, where="queue")
        for fut in (r.fut, *r.followers):
            fut._fail(err)
            if fut._trace is not None:
                fut._trace.finish(error="DeadlineExceeded")
            self.metrics.record_deadline_drop("queue")

    def _collect(self):
        """Pop one coalesced batch (requests, rows) under the admission
        deadline, or None when stopping. Interactive requests pop first;
        batch traffic fills whatever bucket capacity is left — the
        starvation order the QoS contract promises."""
        with self._cond:
            while True:
                while not self._qi and not self._qb:
                    if self._stopping:
                        return None
                    self._cond.wait(timeout=0.1)
                cap = self.model.max_bucket
                head = self._qi[0] if self._qi else self._qb[0]
                deadline = head.fut.t_submit + self._max_wait
                while ((self._qi or self._qb) and self._rows < cap
                       and not self._stopping and not self._draining):
                    now = time.monotonic()
                    if now >= deadline:
                        break
                    self._cond.wait(timeout=min(deadline - now, 0.05))
                reqs, rows = [], 0
                now = time.monotonic()
                for q, interactive in ((self._qi, True), (self._qb, False)):
                    while q and rows + q[0].n <= cap:
                        r = q.popleft()
                        self._rows -= r.n
                        if interactive:
                            self._rows_i -= r.n
                        if self._doomed(r, now):
                            self._drop_doomed_locked(r)
                            continue
                        reqs.append(r)
                        rows += r.n
                if reqs:
                    break  # else: every pop was doomed (or stop() raced)
                if self._stopping and not self._qi and not self._qb:
                    return None
            self._inflight += 1
            t_pop = time.monotonic()
            for r in reqs:   # queue_wait ends here for the whole batch
                if r.fut._trace is not None:
                    r.fut._trace.mark("collected", t_pop)
            return reqs, rows

    def _pad(self, reqs, rows, bucket):
        shape = (bucket,) + self.model.example_shape
        out = _np.zeros(shape, dtype=self.model.dtype)
        off = 0
        for r in reqs:
            out[off:off + r.n] = r.arr
            off += r.n
        return out

    def _collect_loop(self):
        while True:
            batch = self._collect()
            if batch is None:
                return
            reqs, rows = batch
            bucket = self.model.bucket_for(rows)
            x = self._pad(reqs, rows, bucket)
            t_pad = time.monotonic()
            if self._stager is not None:
                # h2d on this thread overlaps the runner's compiled call
                try:
                    x = self._stager.put(x)
                except Exception:
                    pass  # staging is an optimisation; jit transfers too
            t_staged = time.monotonic()
            for r in reqs:   # batch_collect = pad; h2d = the staged put
                if r.fut._trace is not None:
                    r.fut._trace.mark("assembled", t_pad)
                    r.fut._trace.mark("staged", t_staged)
            while True:
                try:
                    self._staged.put((reqs, x, rows, bucket), timeout=0.25)
                    break
                except _qmod.Full:
                    if self._stopping:
                        self._fail_batch(reqs, ServerDrainingError(
                            self.model.name, "stopped"))
                        return

    # ------------------------------------------------------------ runner --
    def _retire_leaders(self, reqs):
        """Unregister each request's content key BEFORE fulfilment so no
        new follower can attach to a request whose followers list is
        being drained (attach happens under the same lock)."""
        with self._cond:
            for r in reqs:
                if r.key is not None and self._leaders.get(r.key) is r:
                    del self._leaders[r.key]

    def _fail_batch(self, reqs, err):
        self._retire_leaders(reqs)
        n = 0
        for r in reqs:
            for fut in (r.fut, *r.followers):
                fut._fail(err)
                if fut._trace is not None:
                    fut._trace.finish(error=type(err).__name__)
                n += 1
        self.metrics.record_fail(n)
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def _run_loop(self):
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        model = self.model
        while True:
            try:
                item = self._staged.get(timeout=0.25)
            except _qmod.Empty:
                if self._stopping and not self._collector.is_alive():
                    return
                continue
            reqs, x, rows, bucket = item

            def run():
                # 'serving.batch' injection: raise = failed batch, hang =
                # the wedged-device scenario the watchdog converts into a
                # crash bundle + StallError, preempt = SIGTERM mid-load
                _faults.point("serving.batch")
                return model.run_versioned(x, rows)

            t0 = time.monotonic()
            for r in reqs:
                if r.fut._trace is not None:
                    r.fut._trace.mark("run_begin", t0)
            try:
                outs, model_version = _watchdog.sync(
                    "serving.batch", run,
                    label=f"{model.name} bucket={bucket} rows={rows}")
            except BaseException as e:
                if isinstance(e, _watchdog.StallError):
                    self.metrics.record_stall()
                self._fail_batch(reqs, RequestError(
                    f"model {model.name!r}: batch of {rows} rows failed: "
                    f"{type(e).__name__}: {e}", cause=e))
                continue
            t_run_end = time.monotonic()
            dur_ms = (t_run_end - t0) * 1e3
            # EWMA execution estimate feeding deadline admission (the
            # "provably cannot meet" proof needs a measured floor)
            self._est_ms = dur_ms if self._est_ms is None \
                else 0.8 * self._est_ms + 0.2 * dur_ms
            self._retire_leaders(reqs)
            off = 0
            now = t_run_end
            for r in reqs:
                sliced = [o[off:off + r.n] for o in outs]
                value = sliced[0] if len(sliced) == 1 else sliced
                if r.fut._trace is not None:
                    r.fut._trace.mark("run_end", t_run_end)
                if self.cache is not None and r.key is not None \
                        and model_version == r.key_version:
                    # insert only when the executing version matches the
                    # version the key names — a flip mid-flight must
                    # never populate the new generation with old math
                    self.cache.put(r.key, value, model_version)
                for fut in (r.fut, *r.followers):
                    fut.model_version = model_version
                    fut._fulfill(value)
                    if fut._trace is not None:
                        fut._trace.finish(bucket=bucket)
                    self.metrics.record_complete(
                        (now - fut.t_submit) * 1e3, fut.priority)
                    if fut.deadline_ms is not None:
                        self.metrics.record_deadline_outcome(
                            (now - fut.t_submit) * 1e3 <= fut.deadline_ms)
                off += r.n
            self.metrics.record_batch(bucket, rows, dur_ms,
                                      self.queue_depth())
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()
