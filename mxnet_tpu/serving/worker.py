"""Serving-fleet worker process: one ModelServer replica behind the router.

The child half of :class:`mxnet_tpu.serving.fleet.ServingFleet` —
launched as ``python -m mxnet_tpu.serving.worker --model-dir DIR`` by the
serving-mode supervisor (:class:`mxnet_tpu.elastic.ServingSupervisor`),
which also sets the gang env (``MXTPU_GANG_DIR`` / ``MXTPU_WORKER_ID`` /
``MXTPU_GANG_GENERATION``) so the heartbeat daemon, telemetry shard and
exit-code excepthook arm themselves at ``import mxnet_tpu``.

Lifecycle::

    load serving.json spec -> ModelContainer -> ModelServer.start()
    -> warmup (disk compile cache: a warm pod loads, never compiles)
    -> HttpFrontEnd on an ephemeral port
    -> atomically announce worker-<slot>.json (port, models, readiness,
       pending-compile census, compile-service stats)
    -> serve until SIGTERM -> drain (answer EVERYTHING admitted)
    -> final announce (admitted/answered) -> exit 75 (EX_TEMPFAIL)

The **announce file** is the router's census record: the fleet only
routes to a worker whose announce says ``ready`` with ``pending_compiles
== 0`` (the rollout health gate), and reads the final announce to prove
a drained generation answered everything it admitted. Live queue depth /
p99 / rps ride separately in the telemetry shard the heartbeat co-writes
every beat.

Model dir layout — one ``serving.json`` describing the served set::

    {"models": [
      {"kind": "demo", "name": "model0", "seed": 0, "dim": 16,
       "hidden": 32, "classes": 10},                  # deterministic MLP
      {"kind": "checkpoint", "name": "m", "prefix": "m", "epoch": 3,
       "example_shape": [16]},                        # save_checkpoint pair
      {"kind": "onnx", "name": "x", "file": "x.onnx",
       "example_shape": [16]}
    ]}

``demo`` models are seeded, so every worker (and every generation served
from the same spec) computes bit-identical responses — the router-
transparency property the fleet tests assert. Relative ``prefix`` /
``file`` paths resolve inside the model dir, which is what makes
``fleet.rollout(new_model_dir)`` a pure pointer swap.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from .. import log as _log

__all__ = ["SPEC_FILE", "demo_spec", "write_spec", "load_container",
           "announce_path", "read_workers", "main"]

_logger = _log.get_logger("mxnet_tpu.serving.worker")

SPEC_FILE = "serving.json"
_ANNOUNCE = "worker-{slot}.json"


# ----------------------------------------------------------- model specs ---

def demo_spec(models=2, dim=16, classes=10, hidden=32, seed=0,
              buckets=None):
    """The loadgen demo-container spec as ``serving.json`` entries: N
    seeded MLPs (same seeds/shapes as ``tools/loadgen.py``'s in-process
    container, so responses are reproducible across workers and
    generations)."""
    entries = []
    for i in range(int(models)):
        entries.append({"kind": "demo", "name": f"model{i}",
                        "seed": int(seed) + i * 101, "dim": int(dim),
                        "hidden": int(hidden) + 8 * i,
                        "classes": int(classes),
                        "buckets": list(buckets) if buckets else None})
    return entries


def write_spec(model_dir, models):
    """Write ``serving.json`` under `model_dir`; returns its path."""
    os.makedirs(os.fspath(model_dir), exist_ok=True)
    path = os.path.join(os.fspath(model_dir), SPEC_FILE)
    # pid+thread-ident tmp name + fsync: a rollout test thread and the
    # main thread may author the same spec concurrently, and a power cut
    # must never publish a half-written spec under os.replace
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump({"models": list(models)}, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def build_demo_model(seed, dim=16, hidden=32, classes=10):
    """One deterministic demo MLP (seeded init — bit-identical across
    processes for the same spec entry)."""
    import mxnet_tpu as mx
    from ..gluon import nn

    mx.random.seed(int(seed))
    net = nn.HybridSequential()
    net.add(nn.Dense(int(hidden), activation="relu"),
            nn.Dense(int(classes)))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, int(dim))))
    return net


def load_container(model_dir):
    """Build the :class:`~mxnet_tpu.serving.model.ModelContainer` a
    worker serves from `model_dir`'s ``serving.json``. Returns
    ``(container, spec)``; raises ValueError naming the offending entry
    on a malformed spec."""
    from .model import ModelContainer

    model_dir = os.fspath(model_dir)
    path = os.path.join(model_dir, SPEC_FILE)
    try:
        with open(path) as f:
            spec = json.load(f)
    except OSError as e:
        raise ValueError(f"no serving spec at {path!r}: {e}") from e
    except ValueError as e:
        raise ValueError(f"malformed serving spec {path!r}: {e}") from e
    entries = spec.get("models")
    if not isinstance(entries, list) or not entries:
        raise ValueError(f"serving spec {path!r} has no 'models' list")
    container = ModelContainer()
    for i, ent in enumerate(entries):
        if not isinstance(ent, dict) or "kind" not in ent \
                or "name" not in ent:
            raise ValueError(f"spec entry #{i} needs 'kind' and 'name': "
                             f"{ent!r}")
        kind, name = ent["kind"], ent["name"]
        buckets = ent.get("buckets") or None
        if kind == "demo":
            dim = int(ent.get("dim", 16))
            net = build_demo_model(ent.get("seed", 0), dim=dim,
                                   hidden=ent.get("hidden", 32),
                                   classes=ent.get("classes", 10))
            container.add_block(name, net, example_shape=(dim,),
                                buckets=buckets)
        elif kind == "checkpoint":
            container.add_checkpoint(
                name, os.path.join(model_dir, ent["prefix"]),
                int(ent.get("epoch", 0)),
                example_shape=tuple(ent["example_shape"]),
                dtype=ent.get("dtype", "float32"), buckets=buckets,
                input_name=ent.get("input_name"))
        elif kind == "onnx":
            container.add_onnx(
                name, os.path.join(model_dir, ent["file"]),
                example_shape=tuple(ent["example_shape"]),
                dtype=ent.get("dtype", "float32"), buckets=buckets,
                input_name=ent.get("input_name"))
        else:
            raise ValueError(
                f"spec entry #{i} ({name!r}): unknown kind {kind!r}; "
                "expected demo | checkpoint | onnx")
    return container, spec


# -------------------------------------------------------- announce files ---

def announce_path(run_dir, slot):
    return os.path.join(os.fspath(run_dir),
                        _ANNOUNCE.format(slot=int(slot)))


def _write_announce(run_dir, slot, payload):
    from .. import elastic as _elastic

    os.makedirs(os.fspath(run_dir), exist_ok=True)
    return _elastic._atomic_json(announce_path(run_dir, slot), payload)


def read_workers(run_dir):
    """Parse every ``worker-<slot>.json`` under `run_dir` into
    ``{slot: record}`` (torn/unreadable files skipped — the writer is
    mid-replace). A multi-host fleet announces into per-host
    ``host-<name>/`` subdirectories; those merge in too (slot ids are
    globally unique across hosts)."""
    out = {}
    run_dir = os.fspath(run_dir)
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    dirs = [run_dir] + sorted(
        os.path.join(run_dir, n) for n in names
        if n.startswith("host-")
        and os.path.isdir(os.path.join(run_dir, n)))
    for d in dirs:
        try:
            entries = names if d == run_dir else os.listdir(d)
        except OSError:
            continue
        for name in entries:
            if not (name.startswith("worker-")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    rec = json.load(f)
                out[int(rec["slot"])] = rec
            except (OSError, ValueError, KeyError, TypeError):
                continue
    return out


# ------------------------------------------------------------ the worker ---

def _serving_compile_stats():
    from .. import compile as _compile

    st = _compile.stats().get("serving", {})
    return {k: st.get(k, 0) for k in ("hits", "misses", "disk_hits",
                                      "compiles", "compile_ms",
                                      "corrupt")}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.serving.worker",
        description="one serving-fleet worker replica (see "
                    "docs/SERVING.md 'Fleet')")
    ap.add_argument("--model-dir", required=True,
                    help="directory holding serving.json (+ model files)")
    ap.add_argument("--slot", type=int,
                    default=int(os.environ.get("MXTPU_WORKER_ID", 0)),
                    help="fleet slot id (default MXTPU_WORKER_ID)")
    ap.add_argument("--generation", type=int,
                    default=int(os.environ.get("MXTPU_GANG_GENERATION",
                                               1)),
                    help="fleet model generation "
                         "(default MXTPU_GANG_GENERATION)")
    ap.add_argument("--run-dir",
                    default=os.environ.get("MXTPU_GANG_DIR"),
                    help="shared fleet dir (announce + heartbeat files; "
                         "default MXTPU_GANG_DIR)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (default 0 = ephemeral, announced)")
    ap.add_argument("--bus-dir",
                    default=os.environ.get("MXTPU_MODELBUS_DIR"),
                    help="model-bus directory to watch for live weight "
                         "updates (default MXTPU_MODELBUS_DIR; unset = "
                         "no bus subscription)")
    ap.add_argument("--bus-poll", type=float, default=0.25,
                    help="bus watcher poll interval, seconds")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the pre-traffic ladder warmup (the worker "
                         "announces pending compiles and the rollout "
                         "health gate will refuse it — a test seam)")
    ap.add_argument("--poll", type=float, default=0.05)
    args = ap.parse_args(argv)
    if not args.run_dir:
        ap.error("no run dir (pass --run-dir or set MXTPU_GANG_DIR)")

    from .. import preempt as _preempt
    from ..telemetry import fleet as _tfleet
    from . import HttpFrontEnd, ModelServer

    t0 = time.monotonic()
    container, spec = load_container(args.model_dir)
    server = ModelServer(container,
                         name=f"fleet-w{args.slot}").start()
    pending = sum(len(m.buckets) for m in container)
    warm_report = None
    if not args.no_warmup:
        warm_report = server.warmup()
        pending = 0
    front = HttpFrontEnd(server, host=args.host, port=args.port).start()
    watcher = None
    if args.bus_dir:
        # live weight streaming: validate + apply bus versions between
        # batches; the ladder compiled above survives every swap
        watcher = server.watch_bus(args.bus_dir, poll=args.bus_poll,
                                   worker=f"w{args.slot}")

    def announce(state, **extra):
        from mxnet_tpu.cluster import proc_start_ticks

        rec = {"slot": args.slot, "generation": args.generation,
               "pid": os.getpid(),
               "start_ticks": proc_start_ticks(os.getpid()),
               "host": args.host, "port": front.port,
               "url": front.url, "model_dir": os.fspath(args.model_dir),
               "models": server.models(), "state": state,
               "ready": state == "serving" and pending == 0,
               "pending_compiles": pending,
               "compile_serving": _serving_compile_stats(),
               "model_bus": watcher.stats() if watcher is not None
               else None,
               "startup_s": round(time.monotonic() - t0, 3),
               "t_wall": time.time()}
        rec.update(extra)
        _write_announce(args.run_dir, args.slot, rec)
        return rec

    # the telemetry shard (written on every heartbeat) carries the HTTP
    # port + slot too, so the fleet scrape can name each worker endpoint
    _tfleet.set_shard_info(http_port=front.port, fleet_slot=args.slot,
                           fleet_generation=args.generation)
    announce("serving", warmup=warm_report)
    _logger.info("fleet worker %d (generation %d): serving %s on %s "
                 "(pending compiles: %d)", args.slot, args.generation,
                 server.models(), front.url, pending)

    _preempt.install()
    try:
        while not _preempt.requested():
            time.sleep(args.poll)
    except KeyboardInterrupt:
        pass  # second-signal path: preempt already flagged the drain
    drained = server.drain(timeout=30.0)
    stats = server.stats()["models"]
    announce("drained", drained=bool(drained),
             admitted=sum(m["submitted"] for m in stats.values()),
             answered=sum(m["completed"] for m in stats.values()),
             failed=sum(m["failed"] for m in stats.values()))
    front.close()
    # records the drain event and raises SystemExit(75) so the
    # serving-mode supervisor retires (or reschedules) the slot
    _preempt.drain(save=False, exit=True)
    return 0  # unreachable: drain() exits


if __name__ == "__main__":
    sys.exit(main())
