"""ModelServer: the multi-tenant front door over a ModelContainer.

One :class:`~mxnet_tpu.serving.batcher.BucketBatcher` per model — so one
model's stall can NEVER block another's queue (per-model collector and
runner threads, per-model admission bounds). The server adds:

* **submit/predict** routing (unknown model → :class:`ModelNotFound`),
* aggregate **stats()** (per-model latency percentiles, throughput,
  queue depth, bucket census, fill ratio — the diagnose "Serving"
  report and the loadgen/bench numbers),
* the **drain** protocol: stop admission, answer every admitted request
  (queued and in flight), stop workers. :meth:`run_until_drained` wires
  it to :mod:`mxnet_tpu.preempt` — a SIGTERM under load finishes what
  was admitted and the process exits 75 (``EX_TEMPFAIL``, the
  reschedule-me code the whole stack uses).

Live servers register in a weak set so ``tools/diagnose.py`` can report
queue depths / rejects / the last drain from inside a serving process.
"""
from __future__ import annotations

import threading
import time
import weakref

from .batcher import BucketBatcher
from .errors import ModelNotFound

__all__ = ["ModelServer", "live_servers", "live_stats"]

_LIVE = weakref.WeakSet()


def live_servers():
    """ModelServer instances alive in this process (diagnose)."""
    return list(_LIVE)


def live_stats():
    """stats() of every live server (diagnose's Serving report)."""
    return [s.stats() for s in live_servers()]


class ModelServer:
    """Serve every model in a :class:`ModelContainer` with continuous
    batching, admission control and bounded tail latency."""

    def __init__(self, container, max_queue=None, max_wait_ms=None,
                 stage=None, cache=None, cache_entries=None,
                 name="mxtpu-server"):
        self.name = name
        self._container = container
        self._overrides = {"max_queue": max_queue,
                           "max_wait_ms": max_wait_ms, "stage": stage,
                           "cache": cache, "cache_entries": cache_entries}
        self._batchers = {}
        self._started = False
        self._draining = False
        self._t_start = None
        self._drain_event = None
        self._bus_watcher = None
        self._lock = threading.Lock()

    # ---------------------------------------------------------- lifecycle --
    def start(self):
        with self._lock:
            if self._started:
                return self
            for model in self._container:
                self._batchers[model.name] = BucketBatcher(
                    model, **self._overrides).start()
            self._started = True
            self._t_start = time.monotonic()
        _LIVE.add(self)
        return self

    def warmup(self):
        """Pre-compile every model's bucket ladder (+ replay the compile
        service's warmup manifest) BEFORE admitting traffic."""
        return self._container.warmup()

    def watch_bus(self, bus, poll=0.25, worker=None):
        """Subscribe this server to a model bus (a directory path or a
        :class:`~mxnet_tpu.modelbus.ModelBus`): a background watcher
        validates each new version (CRC / census / finiteness) and flips
        every census-matching served model between batches — live weight
        updates with zero recompiles (docs/SERVING.md "Online updates").
        Returns the :class:`~mxnet_tpu.modelbus.BusWatcher`."""
        from ..modelbus import BusWatcher

        with self._lock:
            if self._bus_watcher is None:
                self._bus_watcher = BusWatcher(
                    self, bus, poll=poll,
                    worker=worker or self.name).start()
        return self._bus_watcher

    @property
    def bus_watcher(self):
        """The active bus watcher, or None (not subscribed)."""
        return self._bus_watcher

    @property
    def started(self):
        return self._started

    @property
    def draining(self):
        return self._draining

    @property
    def container(self):
        return self._container

    def models(self):
        return list(self._batchers) if self._batchers \
            else self._container.names()

    def model_info(self):
        """Per-model serving metadata: input dtype, weight dtype (int8
        for quantized models), bucket ladder, example shape — the
        ``/v1/models`` detail payload."""
        return {m.name: {"dtype": m.dtype,
                         "weight_dtype": m.weight_dtype,
                         "quantized": m.quantized,
                         "buckets": list(m.buckets),
                         "example_shape": list(m.example_shape)}
                for m in self._container}

    # ------------------------------------------------------------ serving --
    def _batcher(self, model):
        b = self._batchers.get(model)
        if b is None:
            if not self._started:
                raise RuntimeError(f"server {self.name!r} not started")
            raise ModelNotFound(
                f"model {model!r} not served; available: "
                f"{sorted(self._batchers)}")
        return b

    def submit(self, model, arr, priority="interactive", deadline_ms=None):
        """Admit one request; returns a
        :class:`~mxnet_tpu.serving.batcher.ServingFuture`. Fast-rejects
        with ServerBusyError / ServerDrainingError / DeadlineExceeded —
        never queues beyond the per-model bound. ``priority`` is the QoS
        class (interactive | batch); ``deadline_ms`` drops the request
        before it wastes a batch slot when it provably can't be met."""
        return self._batcher(model).submit(arr, priority=priority,
                                           deadline_ms=deadline_ms)

    def predict(self, model, arr, timeout=None, priority="interactive",
                deadline_ms=None):
        """Synchronous submit + bounded wait."""
        return self.submit(model, arr, priority=priority,
                           deadline_ms=deadline_ms).result(timeout)

    # -------------------------------------------------------------- drain --
    def drain(self, timeout=30.0):
        """Stop admission on every model, answer everything admitted,
        stop the workers. Returns True when fully drained in time. The
        SIGTERM path: ``preempt`` raises the flag, the serving loop calls
        this, then exits 75 for the gang scheduler to reschedule."""
        self._draining = True
        if self._bus_watcher is not None:
            self._bus_watcher.stop()   # no weight flips mid-drain
        ok = True
        for b in self._batchers.values():
            ok = b.drain(timeout=timeout) and ok
        answered = sum(b.metrics.completed for b in self._batchers.values())
        failed = sum(b.metrics.failed for b in self._batchers.values())
        for b in self._batchers.values():
            b.stop()
        self._drain_event = {"time": time.time(), "drained": ok,
                             "answered": answered, "failed": failed}
        from .. import profiler as _profiler

        if _profiler._RECORDING:
            _profiler.record_instant(f"serving.{self.name}.drain",
                                     cat="serving", args=self._drain_event)
        return ok

    def stop(self):
        """Hard stop (drainless): queued requests fail. Prefer
        drain() → stop() — stop after a drain is a no-op join."""
        if self._bus_watcher is not None:
            self._bus_watcher.stop()
        for b in self._batchers.values():
            b.stop()
        self._started = False
        _LIVE.discard(self)

    def run_until_drained(self, poll=0.05, install=True, exit=False):
        """Block until a preemption drain is requested (SIGTERM through
        :mod:`mxnet_tpu.preempt`, or ``preempt.request()``), then drain
        and hand off to ``preempt.drain`` — which records the drain event
        and, with ``exit=True``, raises ``SystemExit(75)`` so the
        supervisor reschedules. Returns the drain-event dict when
        ``exit=False``."""
        from .. import preempt as _preempt

        if install:
            _preempt.install()
        while not _preempt.requested():
            time.sleep(poll)
        ok = self.drain()
        ev = _preempt.drain(save=False, exit=exit)
        if isinstance(ev, dict):
            ev["serving"] = dict(self._drain_event or {},
                                 drained=ok)
        return ev

    # -------------------------------------------------------------- stats --
    def stats(self):
        """Aggregate observability snapshot (diagnose / loadgen / bench):
        per-model p50/p95/p99 latency, rps, queue depth, bucket census,
        batch fill ratio, rejects, stalls + the last drain event."""
        models = {}
        for name, b in self._batchers.items():
            models[name] = b.metrics.snapshot(
                queue_depth=b.queue_depth(),
                buckets=list(b.model.buckets),
                dtype=b.model.dtype,
                weight_dtype=b.model.weight_dtype,
                model_version=b.model.version,
                weight_swaps=b.model.swaps,
                draining=b.draining,
                cache=b.cache.stats() if b.cache is not None else None)
        return {
            "name": self.name,
            "started": self._started,
            "draining": self._draining,
            "uptime_s": round(time.monotonic() - self._t_start, 1)
            if self._t_start else None,
            "models": models,
            "model_bus": self._bus_watcher.stats()
            if self._bus_watcher is not None else None,
            "last_drain": self._drain_event,
        }

    def __repr__(self):
        return (f"ModelServer({self.name!r}, "
                f"models={self.models()}, started={self._started})")
