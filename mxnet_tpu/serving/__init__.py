"""Production inference serving: a continuous-batching predict server
with bounded tail latency.

The subsystem the ROADMAP north star ("heavy traffic from millions of
users") asks for, built from the pieces the stack already has:

* **ModelContainer / ServedModel** (``model.py``) — load N models
  (gluon block, symbol+params, ``save_checkpoint`` pair, ONNX) and
  pre-compile a small ladder of padded batch buckets through the
  unified compile service (site ``serving``): persistent disk cache,
  AOT warmup manifest, per-site hit/miss metrics. A warm pod calls
  ``container.warmup()`` and serves with ZERO recompiles.
* **BucketBatcher** (``batcher.py``) — per-model continuous/dynamic
  batching: in-flight requests coalesce into the nearest bucket (pad,
  run, slice) under a ``max_wait_ms`` admission deadline; queue-depth
  admission control fast-rejects with :class:`ServerBusyError` (429)
  instead of queueing unboundedly; h2d staging reuses the
  PrefetchingIter device-put stage so transfer overlaps compute.
* **ModelServer** (``server.py``) — the multi-tenant front:
  submit/predict, per-model isolation (one model's stall never blocks
  another's queue), p50/p95/p99 + throughput + queue depth + bucket
  census + fill-ratio observability, and the SIGTERM drain protocol
  (answer everything admitted, exit 75 via ``preempt``).
* **HttpFrontEnd** (``http.py``) — a small JSON-over-HTTP front so
  external clients / ``tools/loadgen.py``'s socket mode can drive it.
* **Online updates** (``mxnet_tpu.modelbus``) — a training gang streams
  version-stamped weight records into a shared bus directory
  (``ShardedTrainer.publish_to``); ``ModelServer.watch_bus`` validates
  each version (CRC / shape-dtype census / finiteness) and flips the
  served weights between batches with ZERO recompiles, quarantining and
  rolling back poisoned updates (docs/SERVING.md "Online updates").
* **ServingFleet** (``fleet.py`` + ``worker.py``) — N worker processes
  behind one router front door: serving-mode supervision (per-slot
  restart via the exit-code ladder), least-loaded / consistent-hash
  routing with retry-on-connection-refused, telemetry-driven
  autoscaling, and zero-downtime model rollout warmed from the
  persistent compile cache (docs/SERVING.md "Fleet").

Robust by construction: every in-flight batch runs under a
``watchdog.sync("serving.batch", ...)`` deadline (a hung batch produces
a crash bundle + StallError and the server KEEPS SERVING), the
``serving.batch`` fault-injection point lets the chaos harness
(``tools/chaos_smoke.py`` phase 6) exercise all of it, and every client
wait is deadline-bounded (the ``serving-blocking-call`` mxlint rule
gates the no-unbounded-wait contract for this package).

Knobs: the ``MXNET_TPU_SERVING`` env grammar / :func:`configure` (see
``config.py`` and docs/SERVING.md). Quick start::

    from mxnet_tpu import serving

    c = serving.ModelContainer()
    c.add_block("mlp", net, example_shape=(16,))
    server = serving.ModelServer(c).start()
    server.warmup()                       # zero recompiles after this
    y = server.predict("mlp", x)          # or submit() -> future
    server.drain()                        # answer admitted, stop
"""
from .config import configure, configure_from_env, describe, effective
from .errors import (DeadlineExceeded, ModelNotFound, RequestError,
                     RequestTimeout, ServerBusyError, ServerDrainingError,
                     ServingError)
from .metrics import ModelMetrics
from .model import ModelContainer, ServedModel
from .cache import PredictionCache, content_key
from .batcher import BucketBatcher, ServingFuture, PRIORITIES
from .server import ModelServer, live_servers, live_stats

__all__ = [
    "configure", "configure_from_env", "describe", "effective",
    "ServingError", "ModelNotFound", "ServerBusyError",
    "ServerDrainingError", "RequestError", "RequestTimeout",
    "DeadlineExceeded", "ModelMetrics", "ModelContainer", "ServedModel",
    "PredictionCache", "content_key", "BucketBatcher", "ServingFuture",
    "PRIORITIES", "ModelServer", "live_servers", "live_stats",
    "HttpFrontEnd", "ServingFleet", "FleetError",
]


def __getattr__(name):
    if name == "HttpFrontEnd":  # http.server pulled in only when used
        from .http import HttpFrontEnd

        return HttpFrontEnd
    if name in ("ServingFleet", "FleetError"):  # fleet: same laziness
        from . import fleet as _fleet_mod

        return getattr(_fleet_mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
