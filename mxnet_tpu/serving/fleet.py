"""ServingFleet: N ModelServer workers behind one router front door.

The ps-lite scheduler/server split (SURVEY §L7) replayed for inference:
one `ModelServer` process sustains thousands of req/s (PR 8), "millions
of users" needs N of them behind one address. Everything here composes
pieces the stack already has:

* **process plane** — a serving-mode supervisor
  (:class:`mxnet_tpu.elastic.ServingSupervisor`): per-slot restart with
  backoff, heartbeat liveness kills, the exit-code ladder; exit 75 on a
  deliberately drained slot retires it (rollout / scale-down) instead of
  restarting;
* **router** — an HTTP front end dispatching ``POST
  /v1/models/<m>:predict`` to workers over persistent (keep-alive)
  upstream connections. Placement: **least-loaded** (live queue depth
  from each worker's telemetry shard, falling back to round-robin when
  shards are missing/stale), **consistent-hash-by-model** (a vnode hash
  ring — a worker-set change only remaps the keys the lost worker
  owned), or plain round-robin. A connection-refused/reset upstream (a
  dying worker) is retried on a different worker — a request is only
  ever lost if NO worker can take it — and a worker's 503
  (draining/not-admitted) fails over the same way. Upstream timeouts are
  NOT retried: the batch may already be running;
* **autoscaler** — a control loop over the gauges telemetry already
  exports per worker (queue depth / p99 / batch fill / completion rate):
  sustained pressure for K samples scales up, sustained idle scales
  down, min/max bounds and a cooldown damp oscillation
  (``MXNET_TPU_FLEET`` grammar below);
* **zero-downtime rollout** — :meth:`ServingFleet.rollout` starts a
  generation-N+1 worker set from ``new_model_dir`` (warming from the
  persistent compile cache: a warm generation LOADS, never compiles),
  health-gates every new worker (``/healthz`` + an announce census
  showing ZERO pending compiles), shifts router traffic atomically,
  then drains generation N through the exit-75 protocol — mid-load,
  with zero dropped admitted requests.

``MXNET_TPU_FLEET`` env grammar (mirrors FAULTS/WATCHDOG: one variable,
``,``/``;``-separated ``option:value`` entries; constructor kwargs and
``config=`` override)::

    min:<N>            autoscaler lower bound (default 1)
    max:<N>            autoscaler upper bound (default 4; min==max
                       disables autoscaling)
    up_queue:<N>       scale-up pressure: any worker's queue depth >= N
                       (default 32)
    up_p99_ms:<F>      scale-up pressure: any worker's p99 >= F (250)
    up_fill:<F>        scale-up pressure: batch fill ratio >= F (0.98 —
                       full buckets mean the batcher is saturated)
    k:<N>              consecutive pressure samples before scaling up (3)
    idle_rps:<F>       scale-down: fleet completion rate <= F req/s with
                       empty queues (default 1.0)
    idle_k:<N>         consecutive idle samples before scaling down (5)
    cooldown:<F>       seconds after any scale action before the next (10)
    interval:<F>       autoscaler sampling period, seconds (1.0)
    policy:<P>         least_loaded | hash | round_robin (least_loaded)
    beat:<F>           worker heartbeat/telemetry-shard cadence (0.5)
    ready_timeout:<F>  worker-ready / rollout health-gate deadline (120)
    drain_timeout:<F>  generation drain deadline during rollout (60)
    grace:<F>          drain SIGTERM->SIGKILL escalation deadline (15)
    dead_after:<F>     heartbeat-silence kill threshold (30; 0 off)
    restarts:<N>       per-slot restart budget (5)
    timeout_ms:<F>     router upstream request deadline (30000)
    hedge:<0|1>        hedged requests: re-issue a straggling in-flight
                       request to a second worker after the hedge
                       threshold, first answer wins (default 1)
    hedge_factor:<F>   hedge threshold = router p99 x this factor (2.0)
    hedge_min_ms:<F>   hedge threshold floor — also the threshold used
                       against a flagged persistent-straggler worker (20)
    slo_ms:<F>         target p99 SLO: when set (> 0) the autoscaler
                       scales on p99-vs-SLO headroom (pressure at p99 >=
                       80% of the SLO) instead of raw queue depth /
                       fill; 0 keeps the queue-depth policy (default 0)

Multi-host: pass ``hosts=[...]`` to place workers across machines — each
entry is a name (``"local"``), an ssh destination (``"user@h2"``), or a
dict ``{name, ssh, cwd, env, advertise, locality}``. Remote workers are
launched through the same ssh path the gang supervisor uses
(:func:`mxnet_tpu.elastic._ssh_argv`); every host gets its own run
(sub)dir — heartbeats and telemetry shards are merged at scrape — and
the router becomes locality-aware: local workers are preferred, remote
ones take the spill with a measured latency penalty. The 2-host chaos
drill runs two "hosts" on localhost with distinct run dirs; a genuinely
remote host needs this repo importable at the same path (shared
filesystem or an rsynced checkout) and the run dir on shared storage.

Hedging semantics (docs/SERVING.md "Planet scale"): only the FIRST
attempt hedges, and only when the primary is merely *slow* — a primary
that fails fast takes the ordinary failover path, and a primary that
hits the upstream timeout without a hedge already in flight is NEVER
hedged after the fact (the batch may be running; "zero dropped admitted
requests" forbids re-issuing). First answer wins; the loser's connection
is closed (the worker still answers its donating batch — content-keyed
in-flight dedupe on the worker makes the duplicate free when both copies
land on one worker).

Quick start::

    from mxnet_tpu.serving import fleet, worker

    worker.write_spec(model_dir, worker.demo_spec(models=2))
    f = fleet.ServingFleet(model_dir, workers=2).start()
    ...                           # drive f.url like any serving front end
    f.rollout(new_model_dir)      # zero-downtime model swap
    f.stop()

Observability: ``fleet.json`` in the run dir (census, autoscaler state,
rollout history, router counters — the diagnose "Serving Fleet" report),
``mxtpu_fleet_*`` gauges on the router's ``/metrics`` (generation,
ready/desired workers, fleet rps, router/autoscale counters, plus the
per-rank re-exports from :mod:`mxnet_tpu.telemetry.fleet`), and
``fleet.*`` flight events for every lifecycle transition.
"""
from __future__ import annotations

import collections
import hashlib
import http.client
import json
import os
import re
import socket
import sys
import threading
import time
import weakref

from .. import log as _log
from ..telemetry import flight as _flight
from . import worker as _worker
from .errors import ServingError

__all__ = ["ServingFleet", "FleetError", "Autoscaler", "HashRing",
           "order_candidates", "gate_ready", "worker_metrics",
           "hedged_call", "normalize_hosts", "HedgeGovernor",
           "configure", "effective",
           "describe", "live_fleets", "DEFAULTS", "ENV", "POLICIES"]

_logger = _log.get_logger("mxnet_tpu.serving.fleet")

ENV = "MXNET_TPU_FLEET"

POLICIES = ("least_loaded", "hash", "round_robin")

DEFAULTS = {
    "min": 1,
    "max": 4,
    "up_queue": 32,
    "up_p99_ms": 250.0,
    "up_fill": 0.98,
    "k": 3,
    "idle_rps": 1.0,
    "idle_k": 5,
    "cooldown": 10.0,
    "interval": 1.0,
    "policy": "least_loaded",
    "beat": 0.5,
    "ready_timeout": 120.0,
    "drain_timeout": 60.0,
    "grace": 15.0,
    "dead_after": 30.0,
    "restarts": 5,
    "timeout_ms": 30000.0,
    "hedge": 1,
    "hedge_factor": 2.0,
    "hedge_min_ms": 20.0,
    "slo_ms": 0.0,
}

_INT_KEYS = ("min", "max", "up_queue", "k", "idle_k", "restarts", "hedge")
_FLOAT_KEYS = ("up_p99_ms", "up_fill", "idle_rps", "cooldown", "interval",
               "beat", "ready_timeout", "drain_timeout", "grace",
               "dead_after", "timeout_ms", "hedge_factor", "hedge_min_ms",
               "slo_ms")

_cfg_lock = threading.Lock()
_CFG: dict | None = None
_loaded_env = False


class FleetError(ServingError):
    """Fleet-level failure: workers never became ready, a rollout's
    health gate timed out, or the fleet was asked to serve with no
    routable workers."""


def _coerce(key, val):
    if key == "policy":
        v = str(val).strip().lower()
        if v not in POLICIES:
            raise ValueError(f"unknown fleet policy {val!r}; expected one "
                             f"of {POLICIES}")
        return v
    if key in _INT_KEYS:
        n = int(val)
        if n < 0 or (n < 1 and key in ("min", "max")):
            raise ValueError(f"fleet {key} must be >= 1, got {n}")
        return n
    if key in _FLOAT_KEYS:
        f = float(val)
        if f < 0:
            raise ValueError(f"fleet {key} must be >= 0, got {f}")
        return f
    raise ValueError(f"unknown fleet option {key!r}; expected one of "
                     f"{sorted(DEFAULTS)}")


def _parse(spec):
    cfg = dict(DEFAULTS)
    for entry in re.split(r"[;,]", spec):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, val = entry.partition(":")
        key, val = key.strip(), val.strip()
        if not sep or not val:
            raise ValueError(
                f"bad {ENV} entry {entry!r}: expected <option>:<value>")
        cfg[key] = _coerce(key, val)
    if cfg["max"] < cfg["min"]:
        raise ValueError(f"fleet max ({cfg['max']}) < min ({cfg['min']})")
    return cfg


def configure(spec=None, **options):
    """Install a fleet configuration (grammar string, dict, or kwargs on
    top of the defaults); pass nothing to reset to env/defaults."""
    global _CFG, _loaded_env
    if isinstance(spec, dict):
        cfg = dict(DEFAULTS)
        for k, v in spec.items():
            cfg[k] = _coerce(k, v)
    elif spec:
        cfg = _parse(spec)
    else:
        cfg = dict(DEFAULTS)
    for k, v in options.items():
        cfg[k] = _coerce(k, v)
    if cfg["max"] < cfg["min"]:
        raise ValueError(f"fleet max ({cfg['max']}) < min ({cfg['min']})")
    with _cfg_lock:
        _loaded_env = True
        _CFG = cfg
    return dict(cfg)


def _ensure_env():
    global _loaded_env, _CFG
    if _loaded_env:
        return
    with _cfg_lock:
        if _loaded_env:
            return
        _loaded_env = True
        env = os.environ.get(ENV, "")
        if env:
            try:
                _CFG = _parse(env)
            except ValueError as e:
                _logger.warning("ignoring invalid %s: %s", ENV, e)
                _CFG = None


def effective() -> dict:
    """The effective fleet configuration (env-seeded, configure-wins)."""
    _ensure_env()
    cfg = _CFG
    return dict(cfg) if cfg is not None else dict(DEFAULTS)


def describe() -> dict:
    """Knobs + provenance (tools/diagnose.py 'Serving Fleet')."""
    out = effective()
    out["env"] = os.environ.get(ENV, "<unset>")
    return out


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


# ------------------------------------------------------- routing policies --

def _hash32(s):
    return int(hashlib.md5(str(s).encode()).hexdigest()[:8], 16)


class HashRing:
    """Consistent hashing over worker slots (``vnodes`` points per slot):
    removing a worker only remaps the keys that worker owned; the other
    keys keep their placement — the property the fleet's
    consistent-hash-by-model policy needs across worker churn."""

    def __init__(self, slots=(), vnodes=64):
        self.vnodes = int(vnodes)
        self._ring = []            # sorted [(point, slot)]
        self.rebuild(slots)

    def rebuild(self, slots):
        self._ring = sorted(
            (_hash32(f"{slot}:{v}"), slot)
            for slot in set(slots) for v in range(self.vnodes))
        return self

    def lookup(self, key, allowed=None):
        """The slot owning `key` (restricted to `allowed` when given);
        None on an empty ring."""
        ring = self._ring
        if not ring:
            return None
        h = _hash32(key)
        lo, hi = 0, len(ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        for i in range(len(ring)):
            slot = ring[(lo + i) % len(ring)][1]
            if allowed is None or slot in allowed:
                return slot
        return None


def order_candidates(policy, model, slots, depths=None, rr=0, ring=None,
                     localities=None, remote_penalty=0.0):
    """Order the routable `slots` for one request: the head is the
    placement choice, the tail is the failover order.

    * ``least_loaded`` — ascending live queue depth (unknown depth
      counts as 0: a fresh worker has an empty queue), round-robin
      rotation breaking ties; with NO depth known at all this degrades
      to pure round-robin.
    * ``hash`` — the consistent-hash owner of `model` first, the rest
      rotated.
    * ``round_robin`` — rotation by the request counter.

    Locality: with ``localities`` (``{slot: "local"|"remote"}``) the
    router prefers local/ICI workers and spills to remote/DCN ones with
    a MEASURED penalty — ``remote_penalty`` is the observed extra cost
    of a remote hop expressed in queue-rows equivalents (extra latency /
    local service time), so a remote worker only wins the placement when
    it is more than that many rows *less* loaded. Non-depth policies
    stable-partition local candidates first (the hash owner still wins
    its key: determinism beats locality for affinity routing).
    """
    slots = list(slots)
    if not slots:
        return []

    def _remote(s):
        return localities is not None and localities.get(s) == "remote"

    k = rr % len(slots)
    rotated = slots[k:] + slots[:k]
    if policy == "hash" and ring is not None:
        primary = ring.lookup(model, allowed=set(slots))
        rest = [s for s in rotated if s != primary]
        if localities:
            rest = [s for s in rest if not _remote(s)] + \
                [s for s in rest if _remote(s)]
        if primary is None:
            return rest
        return [primary] + rest
    if policy == "least_loaded" and depths \
            and any(depths.get(s) is not None for s in slots):
        return sorted(rotated, key=lambda s: (depths.get(s) or 0)
                      + (remote_penalty if _remote(s) else 0.0))
    if localities:
        return [s for s in rotated if not _remote(s)] + \
            [s for s in rotated if _remote(s)]
    return rotated


def gate_ready(announce):
    """The rollout health gate's announce half: a worker may take
    traffic only when it announced ``serving`` + ``ready`` with ZERO
    pending compiles (an unwarmed ladder would recompile under traffic —
    exactly what a rollout must never do)."""
    return (bool(announce)
            and announce.get("state") == "serving"
            and bool(announce.get("ready"))
            and int(announce.get("pending_compiles") or 0) == 0)


# ------------------------------------------------------------- hedging ----

def hedged_call(primary, hedge, hedge_after, timeout=None):
    """The hedged-request core, pure threading so it table-tests:
    run ``primary()`` on a worker thread; when it has not answered
    within ``hedge_after`` seconds, issue ``hedge()`` too — the first
    SUCCESSFUL answer wins and the loser is abandoned (the caller closes
    the loser's connection; its thread drains into the result record).

    The retry/timeout contract is preserved by construction:

    * a primary that FINISHES (success or error) before the threshold is
      returned as-is, un-hedged — fast failures take the ordinary
      failover path, hedging only covers the slow-but-alive case;
    * once the hedge is in flight, a primary error (including a timeout)
      legally waits for the already-issued hedge — nothing NEW is ever
      issued after a failure;
    * both failing reports the primary's error (so an upstream timeout
      still surfaces as the 504 the no-replay rule demands).

    Returns a record — never raises::

        {"winner": "primary"|"hedge"|None, "value": ..., "hedged": bool,
         "primary_error": exc|None, "hedge_error": exc|None}
    """
    cond = threading.Condition()
    state = {}

    def run(which, fn):
        try:
            out = (True, fn())
        except BaseException as e:     # noqa: BLE001 — recorded, not lost
            out = (False, e)
        with cond:
            state[which] = out
            cond.notify_all()

    def rec(winner=None, value=None, hedged=False):
        prim, hed = state.get("primary"), state.get("hedge")
        return {"winner": winner, "value": value, "hedged": hedged,
                "primary_error": prim[1] if prim and not prim[0] else None,
                "hedge_error": hed[1] if hed and not hed[0] else None}

    threading.Thread(target=run, args=("primary", primary),
                     daemon=True, name="mxtpu-hedge-primary").start()
    with cond:
        cond.wait_for(lambda: "primary" in state, timeout=hedge_after)
        prim = state.get("primary")
    if prim is not None:
        # answered (or failed) before the threshold: no hedge issued
        if prim[0]:
            return rec(winner="primary", value=prim[1])
        return rec()
    threading.Thread(target=run, args=("hedge", hedge),
                     daemon=True, name="mxtpu-hedge-secondary").start()
    deadline = None if timeout is None else time.monotonic() + timeout
    with cond:
        while True:
            prim, hed = state.get("primary"), state.get("hedge")
            if prim is not None and prim[0]:
                return rec(winner="primary", value=prim[1], hedged=True)
            if hed is not None and hed[0]:
                return rec(winner="hedge", value=hed[1], hedged=True)
            if prim is not None and hed is not None:
                return rec(hedged=True)    # both failed: primary's error
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                return rec(hedged=True)    # caller's backstop expired
            cond.wait(timeout=0.25 if left is None else min(left, 0.25))


# ------------------------------------------------------------ multi-host --

def normalize_hosts(hosts):
    """Canonicalise the ``hosts=`` argument into placement records::

        {name, ssh (None = spawn locally), cwd, env, advertise,
         locality ("local" | "remote")}

    Accepted entries: a plain name (``"local"`` / ``"localhost"`` spawn
    locally; anything else is an ssh destination), or a dict with any of
    the keys above. ``advertise`` is the address the worker binds (and
    announces) its HTTP port on — remote hosts default to their ssh host
    part so the router can reach them; local ones stay on loopback."""
    out = []
    seen = set()
    for i, spec in enumerate(hosts or ()):
        if isinstance(spec, str):
            if spec.strip().lower() in ("local", "localhost", "127.0.0.1"):
                spec = {"name": spec.strip().lower()}
            else:
                spec = {"ssh": spec.strip()}
        elif not isinstance(spec, dict):
            raise ValueError(f"bad fleet host spec {spec!r}: expected a "
                             "name/ssh string or a dict")
        else:
            spec = dict(spec)
        bad = set(spec) - {"name", "ssh", "cwd", "env", "advertise",
                           "locality"}
        if bad:
            raise ValueError(f"bad fleet host spec keys {sorted(bad)}; "
                             "expected name/ssh/cwd/env/advertise/locality")
        ssh = spec.get("ssh")
        name = spec.get("name") or \
            (re.sub(r"[^A-Za-z0-9_.-]", "_", ssh) if ssh else f"host{i}")
        if name in seen:
            raise ValueError(f"duplicate fleet host name {name!r}")
        seen.add(name)
        locality = spec.get("locality") or ("remote" if ssh else "local")
        if locality not in ("local", "remote"):
            raise ValueError(f"bad fleet host locality {locality!r}: "
                             "expected 'local' or 'remote'")
        advertise = spec.get("advertise") or \
            ((ssh.rsplit("@", 1)[-1] if ssh else "127.0.0.1"))
        out.append({"name": str(name), "ssh": ssh,
                    "cwd": spec.get("cwd"),
                    "env": dict(spec.get("env") or {}),
                    "advertise": advertise, "locality": locality})
    return out


class _HostPlane:
    """N per-host :class:`~mxnet_tpu.elastic.ServingSupervisor`\\ s
    behind the single-supervisor surface the fleet drives: every call
    routes by the fleet's slot->host assignment, census/slots/events
    merge (slot ids are globally unique, so a union is exact)."""

    def __init__(self, sups, slot_host):
        self._sups = sups          # {host name: ServingSupervisor}
        self._slot_host = slot_host  # the fleet's live slot->host map

    def _for(self, slot):
        return self._sups[self._slot_host[slot]]

    def spawn(self, slot, generation):
        return self._for(slot).spawn(slot, generation)

    def drain_slot(self, slot, reason=""):
        return self._for(slot).drain_slot(slot, reason=reason)

    def kill_slot(self, slot):
        return self._for(slot).kill_slot(slot)

    def poll(self):
        out = {}
        for sup in self._sups.values():
            out.update(sup.poll())
        return out

    def census(self):
        out = {}
        for sup in self._sups.values():
            out.update(sup.census())
        return out

    def stop_all(self, graceful=True, timeout=None):
        for sup in self._sups.values():
            sup.stop_all(graceful=graceful, timeout=timeout)

    @property
    def slots(self):
        out = {}
        for sup in self._sups.values():
            out.update(sup.slots)
        return out

    @property
    def events(self):
        out = []
        for sup in self._sups.values():
            out.extend(sup.events)
        return sorted(out, key=lambda ev: ev.get("t_wall", 0.0))

    @property
    def restarts_total(self):
        return sum(s.restarts_total for s in self._sups.values())

    @property
    def drained_total(self):
        return sum(s.drained_total for s in self._sups.values())


class HedgeGovernor:
    """Router-side latency book-keeping + hedge planning, shared by
    :class:`ServingFleet` and the cluster reconciler's serving-fleet
    role (both drive the same ``_RouterFront``): the p99 ring feeding
    the hedge threshold, per-slot EWMAs feeding persistent-straggler
    flags (same env knobs as the gang detector —
    ``MXNET_TPU_STRAGGLER_FACTOR`` / ``_PERSIST``), per-locality EWMAs
    feeding the remote spill penalty, and the fired/won/lost/failed
    counters. Pure state + arithmetic, so it table-tests."""

    def __init__(self, cfg, locality_of=None):
        self.cfg = cfg
        self._locality_of = locality_of or (lambda slot: "local")
        self._lock = threading.Lock()
        self.ring = collections.deque(maxlen=512)
        self._slot_ewma = {}       # slot -> (ewma_ms, samples)
        self._loc_ewma = {}        # locality -> ewma_ms
        self._streak = {}
        self.stragglers = frozenset()
        self.counters = {"fired": 0, "won": 0, "lost": 0, "failed": 0}

    def note(self, slot, ms):
        """One completed router request against `slot` took `ms`
        end-to-end."""
        ms = float(ms)
        loc = self._locality_of(slot)
        with self._lock:
            self.ring.append(ms)
            e, n = self._slot_ewma.get(slot, (None, 0))
            self._slot_ewma[slot] = (
                ms if e is None else 0.8 * e + 0.2 * ms, n + 1)
            le = self._loc_ewma.get(loc)
            self._loc_ewma[loc] = ms if le is None \
                else 0.8 * le + 0.2 * ms

    def count(self, outcome):
        with self._lock:
            self.counters[outcome] = self.counters.get(outcome, 0) + 1

    def remote_penalty(self):
        """The measured extra cost of a remote hop, in queue-rows
        equivalents: (remote EWMA - local EWMA) / local EWMA. Zero until
        both localities have answered requests."""
        with self._lock:
            local = self._loc_ewma.get("local")
            remote = self._loc_ewma.get("remote")
        if not local or not remote:
            return 0.0
        return max(0.0, (remote - local) / max(local, 1e-3))

    def threshold(self, slot):
        """Milliseconds to wait before hedging a first attempt against
        `slot`, or None (not enough signal yet). A flagged persistent
        straggler gets the ``hedge_min_ms`` floor immediately; otherwise
        the router's own p99 x ``hedge_factor``, floored at
        ``hedge_min_ms`` and capped at half the upstream timeout (a
        hedge that can't finish inside the remaining budget is
        pointless)."""
        if slot in self.stragglers:
            return self.cfg["hedge_min_ms"]
        with self._lock:
            ring = sorted(self.ring)
        if len(ring) < 16:
            return None
        p99 = ring[int(0.99 * (len(ring) - 1))]
        thr = max(self.cfg["hedge_min_ms"],
                  p99 * self.cfg["hedge_factor"])
        return min(thr, self.cfg["timeout_ms"] / 2.0)

    # one request in PROBE_EVERY keeps its natural placement even when
    # that placement is a flagged straggler: the probe is hedged at the
    # hedge_min_ms floor (cheap rescue), and a RECOVERED slot wins its
    # own probe races, decaying its EWMA until the flag clears —
    # without probes a flagged slot could never prove itself healthy
    PROBE_EVERY = 16

    def reorder(self, order, rr):
        """Stable-move flagged persistent stragglers to the tail of the
        candidate `order` — they stay reachable (failover, hedges) but
        stop being anyone's first choice. Every ``PROBE_EVERY``-th
        request passes through unmoved as a canary probe."""
        flagged = self.stragglers
        if not flagged or rr % self.PROBE_EVERY == 0:
            return order
        return [s for s in order if s not in flagged] + \
            [s for s in order if s in flagged]

    def plan(self, slot, candidates, endpoint):
        """(hedge slot, threshold ms) for a first attempt against
        `slot`, or (None, None) when hedging is off / there is no second
        candidate with a live `endpoint` / the latency signal is too
        thin."""
        if not self.cfg.get("hedge") or len(candidates) < 2:
            return None, None
        thr = self.threshold(slot)
        if thr is None:
            return None, None
        for cand in candidates:
            if cand != slot and endpoint(cand) is not None:
                return cand, thr
        return None, None

    def update_stragglers(self, active):
        """Advance the per-slot flag streaks (call once per control
        interval): a slot whose latency EWMA stayed >= factor x the
        fleet median for `persist` consecutive calls is flagged."""
        factor = _env_float("MXNET_TPU_STRAGGLER_FACTOR", 1.5)
        persist = int(_env_float("MXNET_TPU_STRAGGLER_PERSIST", 3))
        active = set(active) | set(self.stragglers)
        with self._lock:
            ew = {s: e for s, (e, n) in self._slot_ewma.items()
                  if n >= 5 and s in active}
        if len(ew) < 2:
            self._streak = {}
            self.stragglers = frozenset()
            return self.stragglers
        # lower-middle median: with an even count (the 2-host fleet!)
        # the upper-middle would BE the straggler's own EWMA and the
        # flag could never fire
        vals = sorted(ew.values())
        median = vals[(len(vals) - 1) // 2]
        flagged_now = {s for s, e in ew.items()
                       if e >= factor * max(median, 1e-9)}
        self._streak = {s: self._streak.get(s, 0) + 1
                        for s in flagged_now}
        new = frozenset(s for s, n in self._streak.items()
                        if n >= persist)
        for s in sorted(new - self.stragglers):
            _flight.rec("fleet.straggler", f"slot{s}",
                        f"ewma {ew[s]:.1f}ms >= {factor:g}x median "
                        f"{median:.1f}ms")
        self.stragglers = new
        return self.stragglers

    def describe(self):
        """{hedges, stragglers, router_latency} for stats()/diagnose."""
        with self._lock:
            counters = dict(self.counters)
            ring = sorted(self.ring)
            by_loc = {k: round(v, 3) for k, v in self._loc_ewma.items()}
        lat = None
        if ring:
            lat = {"samples": len(ring),
                   "p50_ms": round(ring[len(ring) // 2], 3),
                   "p99_ms": round(ring[int(0.99 * (len(ring) - 1))], 3),
                   "by_locality_ewma_ms": by_loc}
        return {"hedges": counters,
                "stragglers": sorted(self.stragglers),
                "router_latency": lat}


# ---------------------------------------------------------- shard reading --

def _series_values(shard, name, **match):
    out = []
    metric = (shard.get("metrics") or {}).get(name)
    if not isinstance(metric, dict):
        return out
    for series in metric.get("series") or ():
        labels = series.get("labels") or {}
        if all(labels.get(k) == v for k, v in match.items()):
            v = series.get("value")
            if isinstance(v, (int, float)):
                out.append(float(v))
    return out


def worker_metrics(run_dir, slots=None):
    """Per-worker serving gauges from the telemetry shards each worker
    co-writes with its heartbeat: ``{slot: {queue_depth, p99_ms, fill,
    completed, rps, age_s, generation}}``. Missing/torn shards are
    simply absent — callers fall back (router: round-robin; autoscaler:
    no pressure signal from that worker)."""
    from ..telemetry import fleet as _tfleet

    out = {}
    now = time.time()
    for rank, shard in _tfleet.read_shards(run_dir).items():
        if slots is not None and rank not in slots:
            continue
        depth = _series_values(shard, "mxtpu_serving_queue_depth")
        p99 = _series_values(shard, "mxtpu_serving_latency_ms",
                             quantile="p99")
        fill = _series_values(shard, "mxtpu_serving_batch_fill_ratio")
        done = _series_values(shard, "mxtpu_serving_requests_total",
                              outcome="completed")
        rps = _series_values(shard, "mxtpu_serving_rps")
        out[rank] = {
            "queue_depth": sum(depth) if depth else None,
            "p99_ms": max(p99) if p99 else None,
            "fill": max(fill) if fill else None,
            "completed": sum(done) if done else 0.0,
            "rps": sum(rps) if rps else None,
            "age_s": round(now - float(shard.get("t_wall", now)), 3),
            "generation": shard.get("generation"),
        }
    return out


# -------------------------------------------------------------- autoscaler --

class Autoscaler:
    """The scaling decision core, pure enough to table-test: feed it one
    aggregate sample per interval and it answers up/down/None.

    Pressure (any of: max queue depth >= ``up_queue``, max p99 >=
    ``up_p99_ms``, max batch fill >= ``up_fill``) sustained for ``k``
    consecutive samples scales up; idleness (completion rate <=
    ``idle_rps`` AND empty queues) sustained for ``idle_k`` samples
    scales down; every action starts a ``cooldown`` window during which
    streaks keep accumulating but nothing fires; ``min``/``max`` bound
    the census.

    SLO mode (``slo_ms`` > 0): pressure becomes p99-vs-SLO **headroom**
    instead of the raw queue/fill thresholds — the fleet scales up when
    p99 eats 80% of the SLO budget, i.e. *before* the SLO is breached,
    not after the queue is already deep (a deep queue means the p99 the
    clients saw was already lost). Idleness is unchanged: completion
    rate is the only trustworthy scale-down signal either way."""

    def __init__(self, cfg=None):
        self.cfg = dict(effective() if cfg is None else cfg)
        self.up_streak = 0
        self.idle_streak = 0
        self.cooldown_until = 0.0
        self.last = None           # last decision record (incl. holds)
        self.last_action = None    # last actual up/down
        self.decisions = {"up": 0, "down": 0}

    def decide(self, sample, workers, now=None):
        """One sample -> ("up"|"down"|None, record). `sample` carries
        ``queue_depth``/``p99_ms``/``fill`` (fleet-max) + ``rps``
        (fleet completion rate); `workers` is the current census."""
        cfg = self.cfg
        now = time.monotonic() if now is None else now
        pressure = []
        q = sample.get("queue_depth")
        p99 = sample.get("p99_ms")
        slo = cfg.get("slo_ms") or 0.0
        if slo > 0:
            # SLO mode: the only up-pressure is exhausted p99 headroom
            budget = 0.8 * slo
            if p99 is not None and p99 >= budget:
                pressure.append(
                    f"p99 {p99:g}ms >= 80% of {slo:g}ms SLO")
        else:
            if q is not None and q >= cfg["up_queue"]:
                pressure.append(f"queue {q:g} >= {cfg['up_queue']}")
            if p99 is not None and p99 >= cfg["up_p99_ms"]:
                pressure.append(f"p99 {p99:g}ms >= {cfg['up_p99_ms']:g}")
            fill = sample.get("fill")
            if fill is not None and fill >= cfg["up_fill"]:
                pressure.append(f"fill {fill:g} >= {cfg['up_fill']:g}")
        rps = sample.get("rps")
        # idleness takes PRECEDENCE over pressure: p99/fill are
        # recent-window gauges that stay high after traffic stops — an
        # empty-queue fleet completing nothing is idle no matter what
        # its stale latency gauges say
        idle = (rps is not None and rps <= cfg["idle_rps"] and not q)
        if idle:
            self.idle_streak += 1
            self.up_streak = 0
        elif pressure:
            self.up_streak += 1
            self.idle_streak = 0
        else:
            self.up_streak = 0
            self.idle_streak = 0
        direction, why = None, None
        if self.up_streak >= cfg["k"]:
            if workers >= cfg["max"]:
                why = f"at max ({cfg['max']})"
            elif now < self.cooldown_until:
                why = "cooling down"
            else:
                direction = "up"
                why = "; ".join(pressure)
        elif self.idle_streak >= cfg["idle_k"]:
            if workers <= cfg["min"]:
                why = f"at min ({cfg['min']})"
            elif now < self.cooldown_until:
                why = "cooling down"
            else:
                direction = "down"
                why = (f"idle: rps {rps:g} <= {cfg['idle_rps']:g} for "
                       f"{self.idle_streak} samples")
        rec = {"t_wall": time.time(), "direction": direction,
               "reason": why, "workers": workers,
               "up_streak": self.up_streak,
               "idle_streak": self.idle_streak,
               "sample": {k: sample.get(k) for k in
                          ("queue_depth", "p99_ms", "fill", "rps")}}
        self.last = rec
        if direction is not None:
            self.cooldown_until = now + cfg["cooldown"]
            self.up_streak = 0
            self.idle_streak = 0
            self.decisions[direction] += 1
            self.last_action = rec
        return direction, rec

    def describe(self):
        return {"last": self.last, "last_action": self.last_action,
                "decisions": dict(self.decisions),
                "up_streak": self.up_streak,
                "idle_streak": self.idle_streak,
                "enabled": self.cfg["max"] > self.cfg["min"]}


# ------------------------------------------------------------- the router --

_PREDICT_RE = re.compile(r"^/(?:v1/models|models|predict)/([^/:]+)"
                         r"(?::predict)?$")

#: upstream failures safe to retry on ANOTHER worker: the connection
#: died before (or instead of) a response — the request was never
#: admitted there. A timeout is NOT in this set: the batch may already
#: be running, and "zero dropped admitted requests" forbids guessing.
_RETRYABLE = (ConnectionError, http.client.HTTPException,
              socket.gaierror)


class _RouterFront:
    """The fleet's HTTP front door: proxies predict traffic to workers
    over persistent per-thread upstream connections, retrying
    connection-level failures (and worker 503s — not-admitted by
    construction) on the next candidate."""

    def __init__(self, fleet, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        self._fleet = fleet
        self._local = threading.local()
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "mxtpu-fleet/0.1"
            # keep-alive + separate header/body sends otherwise hit the
            # Nagle x delayed-ACK 40ms stall — even on loopback
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass

            def _json(self, code, payload, extra_headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                fl = front._fleet
                if self.path == "/healthz":
                    st = fl.stats(light=True)
                    ok = st["ready"] >= 1
                    self._json(200 if ok else 503,
                               {"status": "ok" if ok else "degraded",
                                "generation": st["generation"],
                                "workers_ready": st["ready"],
                                "workers_desired": st["desired"]})
                elif self.path in ("/v1/models", "/models"):
                    self._json(200, fl.models())
                elif self.path in ("/v1/stats", "/stats"):
                    self._json(200, fl.stats())
                elif self.path == "/metrics":
                    from ..telemetry import export as _export

                    body = _export.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     _export.PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics.json":
                    from ..telemetry import export as _export

                    body = _export.render_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": f"no route {self.path!r}"})

            def do_POST(self):
                m = _PREDICT_RE.match(self.path)
                if not m:
                    self._json(404, {"error": f"no route {self.path!r}"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                rid = self.headers.get("X-Request-Id")
                if not rid:
                    from ..telemetry import trace as _trace

                    rid = _trace.new_request_id()
                status, payload, hdrs = front._dispatch(
                    m.group(1), self.path, body,
                    self.headers.get("Content-Type", "application/json"),
                    rid)
                self.send_response(status)
                for k, v in hdrs:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = None

    # ------------------------------------------------------- dispatching --
    def _conn_to(self, slot, endpoint):
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn, ep = conns.get(slot, (None, None))
        if conn is None or ep != endpoint:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            conn = http.client.HTTPConnection(
                endpoint[0], endpoint[1],
                timeout=self._fleet.cfg["timeout_ms"] / 1e3)
            conn.connect()
            # persistent upstream: TCP_NODELAY or every request eats the
            # Nagle x delayed-ACK stall
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                 1)
            conns[slot] = (conn, endpoint)
        return conn

    def _drop_conn(self, slot):
        conns = getattr(self._local, "conns", None)
        if conns:
            conn, _ = conns.pop(slot, (None, None))
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _fresh_conn(self, endpoint):
        """A one-shot upstream connection (hedges ride these so a
        cancelled loser never poisons the per-thread keep-alive pool)."""
        conn = http.client.HTTPConnection(
            endpoint[0], endpoint[1],
            timeout=self._fleet.cfg["timeout_ms"] / 1e3)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    @staticmethod
    def _forward_on(conn, path, body, ctype, rid):
        """One upstream POST on an explicit connection. Returns
        ``(status, payload, content_type, retry_after)``; raises the
        connection-level failures the dispatch ladder classifies."""
        conn.request("POST", path, body=body,
                     headers={"Content-Type": ctype,
                              "X-Request-Id": rid})
        resp = conn.getresponse()
        payload = resp.read()
        return (resp.status, payload,
                resp.getheader("Content-Type", "application/json"),
                resp.getheader("Retry-After", "0.1"))

    def _dispatch(self, model, path, body, ctype, rid):
        """Route one admitted-at-the-front-door request: walk the
        policy-ordered candidates; connection-level failures and 503s
        fail over to the next worker; the LAST candidate's verdict (or a
        fleet 503) goes back to the client. The first attempt may be
        HEDGED: when the primary is slower than the hedge threshold
        (router p99 x hedge_factor, floored at hedge_min_ms, immediate
        floor for flagged stragglers) the same request is issued to the
        next candidate and the first answer wins."""
        fleet = self._fleet
        fleet._count("requests")
        rid_hdr = [("X-Request-Id", rid)]
        from .. import faults as _faults

        try:
            # 'serving.route' injection: delay = a slow route (drills
            # the hedge threshold), raise = a broken router hop
            _faults.point("serving.route")
        except Exception as e:
            fleet._count("errors")
            return 500, json.dumps(
                {"error": f"router fault: {type(e).__name__}: {e}",
                 "request_id": rid}).encode(), \
                rid_hdr + [("Content-Type", "application/json")]
        candidates = fleet.pick(model)
        if not candidates:
            fleet._count("rejects")
            return 503, json.dumps(
                {"error": "no ready workers in the fleet",
                 "request_id": rid}).encode(), \
                rid_hdr + [("Content-Type", "application/json"),
                           ("Retry-After", "1")]
        last_err = None
        t_req = time.monotonic()
        for attempt, slot in enumerate(candidates):
            endpoint = fleet.endpoint(slot)
            if endpoint is None:
                continue
            if attempt:
                fleet._count("retries")
            hedge_slot = hedge_ep = None
            if attempt == 0:
                hedge_slot, hedge_after_ms = fleet.hedge_plan(
                    slot, candidates)
                hedge_ep = fleet.endpoint(hedge_slot) \
                    if hedge_slot is not None else None
            used = slot
            try:
                conn = self._conn_to(slot, endpoint)
            except _RETRYABLE + (OSError,) as e:
                self._drop_conn(slot)
                fleet.mark_suspect(slot, repr(e))
                last_err = f"{type(e).__name__}: {e}"
                continue
            if hedge_ep is None:
                try:
                    status, payload, up_ctype, retry_after = \
                        self._forward_on(conn, path, body, ctype, rid)
                except socket.timeout:
                    # maybe admitted: do NOT replay on another worker
                    self._drop_conn(slot)
                    fleet._count("errors")
                    return 504, json.dumps(
                        {"error": f"worker {slot} timed out",
                         "request_id": rid}).encode(), \
                        rid_hdr + [("Content-Type", "application/json")]
                except _RETRYABLE + (OSError,) as e:
                    self._drop_conn(slot)
                    fleet.mark_suspect(slot, repr(e))
                    last_err = f"{type(e).__name__}: {e}"
                    continue
            else:
                hedge_holder = {}

                def run_primary(c=conn):
                    return self._forward_on(c, path, body, ctype, rid)

                def run_hedge(ep=hedge_ep):
                    hc = self._fresh_conn(ep)
                    hedge_holder["conn"] = hc
                    return self._forward_on(hc, path, body, ctype, rid)

                res = hedged_call(
                    run_primary, run_hedge,
                    hedge_after=hedge_after_ms / 1e3,
                    timeout=fleet.cfg["timeout_ms"] / 1e3 * 1.5 + 1.0)
                if res["hedged"]:
                    fleet._count_hedge("fired")
                    _flight.rec("fleet.hedge", f"slot{slot}",
                                f"-> slot{hedge_slot} after "
                                f"{hedge_after_ms:.0f}ms")
                if res["hedge_error"] is not None:
                    fleet._count_hedge("failed")
                winner = res["winner"]
                if winner is None:
                    pe = res["primary_error"]
                    self._drop_conn(slot)
                    hc = hedge_holder.get("conn")
                    if hc is not None:
                        try:
                            hc.close()
                        except OSError:
                            pass
                    if isinstance(pe, socket.timeout):
                        # primary timed out and the (already-issued)
                        # hedge could not answer either — 504, nothing
                        # is replayed after a timeout
                        fleet._count("errors")
                        return 504, json.dumps(
                            {"error": f"worker {slot} timed out "
                             "(hedge failed too)",
                             "request_id": rid}).encode(), \
                            rid_hdr + [("Content-Type",
                                        "application/json")]
                    fleet.mark_suspect(slot, repr(pe))
                    if hedge_slot is not None \
                            and res["hedge_error"] is not None:
                        fleet.mark_suspect(hedge_slot,
                                           repr(res["hedge_error"]))
                    last_err = f"{type(pe).__name__}: {pe}" \
                        if pe is not None else "hedged call timed out"
                    continue
                if winner == "hedge":
                    fleet._count_hedge("won")
                    used = hedge_slot
                    # the loser primary still holds the pooled conn: it
                    # may answer later — close it so the pool can't
                    # serve a stale response to the next request
                    self._drop_conn(slot)
                elif res["hedged"]:
                    fleet._count_hedge("lost")
                    hc = hedge_holder.get("conn")
                    if hc is not None:
                        try:
                            hc.close()
                        except OSError:
                            pass
                status, payload, up_ctype, retry_after = res["value"]
            if status == 503 and attempt + 1 < len(candidates):
                # draining worker: the request was NOT admitted there
                continue
            if 200 <= status < 300:
                fleet._count("completed")
                fleet.note_latency(used, (time.monotonic() - t_req) * 1e3)
            hdrs = rid_hdr + [("Content-Type", up_ctype)]
            if status in (429, 503):
                hdrs.append(("Retry-After", retry_after))
            return status, payload, hdrs
        fleet._count("rejects")
        return 503, json.dumps(
            {"error": "every fleet worker refused the request",
             "last_error": last_err, "request_id": rid}).encode(), \
            rid_hdr + [("Content-Type", "application/json"),
                       ("Retry-After", "1")]

    # ---------------------------------------------------------- lifecycle --
    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1}, daemon=True,
                name="mxtpu-fleet-router")
            self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# --------------------------------------------------------------- the fleet --

_LIVE = weakref.WeakSet()
_collector_installed = False


def live_fleets():
    """ServingFleet instances alive in this process (diagnose)."""
    return list(_LIVE)


class ServingFleet:
    """Supervise N serving workers behind one router (docs/SERVING.md
    "Fleet"). The three control surfaces — per-slot supervision,
    telemetry-driven autoscaling, zero-downtime rollout — run on one
    monitor thread; the router serves on its own HTTP threads."""

    def __init__(self, model_dir, workers=None, *, run_dir=None,
                 policy=None, host="127.0.0.1", port=0, config=None,
                 warmup=True, env=None, cwd=None, name="fleet",
                 bus_dir=None, hosts=None, popen=None):
        import tempfile

        cfg = dict(effective())
        if isinstance(config, str):
            cfg.update(_parse(config))
        elif config:
            for k, v in config.items():
                cfg[k] = _coerce(k, v)
        if policy is not None:
            cfg["policy"] = _coerce("policy", policy)
        self.cfg = cfg
        self.name = str(name)
        self.model_dir = os.fspath(model_dir)
        self.run_dir = os.fspath(
            run_dir or tempfile.mkdtemp(prefix="mxtpu_fleet_"))
        os.makedirs(self.run_dir, exist_ok=True)
        self._initial_workers = max(1, int(cfg["min"]
                                           if workers is None else workers))
        self._host, self._port = host, int(port)
        self._warmup = bool(warmup)
        self.generation = 0
        self.state = "idle"
        self._gen_dirs = {}        # generation -> model dir
        self._desired = {}         # slot -> generation
        self._next_slot = 0
        self._routable = []        # slots taking traffic right now
        self._endpoints = {}       # slot -> (host, port)
        self._suspect = {}         # slot -> monotonic deadline
        self._rr = 0
        self._ring = HashRing()
        self.rollouts = []
        self._counters = {"requests": 0, "completed": 0, "retries": 0,
                          "rejects": 0, "errors": 0}
        self._count_lock = threading.Lock()
        self._hedge = HedgeGovernor(cfg, self._slot_locality)
        self._scaler = Autoscaler(cfg)
        self._last_completed = None   # (t_mono, fleet completed total)
        self._last_sample = {}
        self._lock = threading.RLock()      # census + rollout/scale
        self._stop_evt = threading.Event()
        self._monitor = None
        self._router = None
        self._summary_at = 0.0

        worker_env = dict(env or {})
        worker_env.setdefault("MXNET_TPU_GANG_BEAT", str(cfg["beat"]))
        # workers must find this package without an installed dist
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        worker_env["PYTHONPATH"] = pkg_root + os.pathsep + \
            os.environ.get("PYTHONPATH", "")
        # a shared persistent compile cache is what makes rollout cheap:
        # generation N+1 LOADS the ladder the first generation compiled
        worker_env.setdefault("MXNET_TPU_CACHE_DIR",
                              os.environ.get("MXNET_TPU_CACHE_DIR")
                              or os.path.join(self.run_dir, "cache"))
        # diagnose run next to the fleet finds the run dir through this
        worker_env.setdefault("MXTPU_FLEET_DIR", self.run_dir)
        # live weight streaming: every worker of every generation
        # subscribes to the same bus (the trainer's publish_to target)
        self.bus_dir = os.fspath(bus_dir) if bus_dir \
            else os.environ.get("MXTPU_MODELBUS_DIR")
        if self.bus_dir:
            worker_env.setdefault("MXTPU_MODELBUS_DIR", self.bus_dir)

        from .. import elastic as _elastic

        self._worker_env = worker_env
        self.hosts = normalize_hosts(hosts) if hosts else None
        self._slot_host = {}       # slot -> host name (multi-host only)
        if self.hosts is None:
            self._sup = _elastic.ServingSupervisor(
                self._command_for, self.run_dir, grace=cfg["grace"],
                dead_after=cfg["dead_after"],
                max_restarts=cfg["restarts"],
                env=worker_env, cwd=cwd, popen=popen)
        else:
            self._by_host = {h["name"]: h for h in self.hosts}
            sups = {}
            for h in self.hosts:
                h["run_dir"] = os.path.join(self.run_dir,
                                            f"host-{h['name']}")
                os.makedirs(h["run_dir"], exist_ok=True)
                henv = dict(worker_env)
                henv.update(h["env"])
                sups[h["name"]] = _elastic.ServingSupervisor(
                    self._host_command_for(h), h["run_dir"],
                    grace=cfg["grace"], dead_after=cfg["dead_after"],
                    max_restarts=cfg["restarts"], env=henv,
                    cwd=(h["cwd"] if not h["ssh"] else None) or cwd,
                    popen=popen)
            self._sup = _HostPlane(sups, self._slot_host)

        from ..telemetry import fleet as _tfleet

        _tfleet.install(self.run_dir)
        _install_collector()
        _LIVE.add(self)
        self._t_start = time.monotonic()

    # -------------------------------------------------------- worker cmds --
    def _command_for(self, slot, generation):
        cmd = [sys.executable, "-m", "mxnet_tpu.serving.worker",
               "--model-dir", self._gen_dirs[generation],
               "--slot", str(slot), "--generation", str(generation)]
        if not self._warmup:
            cmd.append("--no-warmup")
        return cmd

    def _host_command_for(self, host):
        """The per-host supervisor's command factory: the worker argv
        carries run-dir/slot/generation/bind-address EXPLICITLY (an ssh
        child does not inherit the local supervisor env), and an ssh
        host wraps it in the same ``ssh -tt ... exec env ...`` launch
        the gang supervisor uses — so a remote worker still heartbeats
        and announces into its (shared-filesystem) host dir."""

        def command_for(slot, generation):
            argv = [sys.executable, "-m", "mxnet_tpu.serving.worker",
                    "--model-dir", self._gen_dirs[generation],
                    "--run-dir", host["run_dir"],
                    "--slot", str(slot),
                    "--generation", str(generation),
                    "--host", host["advertise"]]
            if not self._warmup:
                argv.append("--no-warmup")
            if not host["ssh"]:
                return argv
            from .. import elastic as _elastic

            env = dict(self._worker_env)
            env.update(host["env"])
            env.update({"MXTPU_GANG_DIR": host["run_dir"],
                        "MXTPU_WORKER_ID": str(slot),
                        "MXTPU_GANG_GENERATION": str(generation),
                        "MXNET_TPU_PREEMPT": "1"})
            return _elastic._ssh_argv(host["ssh"], env, argv,
                                      cwd=host["cwd"])

        return command_for

    def _pick_host(self):
        """Least-populated host wins the next slot (definition order
        breaks ties) — the fleet stays balanced through scale-up,
        rollout and per-slot restarts alike. Caller holds ``_lock``."""
        counts = {h["name"]: 0 for h in self.hosts}
        for s, hn in self._slot_host.items():
            if s in self._desired and hn in counts:
                counts[hn] += 1
        return min(self.hosts, key=lambda h: counts[h["name"]])["name"]

    def _spawn(self, generation):
        with self._lock:
            slot = self._next_slot
            self._next_slot += 1
            self._desired[slot] = int(generation)
            if self.hosts is not None:
                self._slot_host[slot] = self._pick_host()
        self._sup.spawn(slot, generation)
        return slot

    def _slot_locality(self, slot):
        if self.hosts is None:
            return "local"
        h = self._by_host.get(self._slot_host.get(slot))
        return h["locality"] if h else "local"

    def _slot_ssh(self, slot):
        if self.hosts is None:
            return None
        h = self._by_host.get(self._slot_host.get(slot))
        return h["ssh"] if h else None

    # ---------------------------------------------------------- lifecycle --
    def start(self, wait_ready=True, timeout=None):
        """Spawn the initial generation, start the router + monitor;
        with ``wait_ready`` (default) block until every worker passed
        the health gate (or raise :class:`FleetError`)."""
        with self._lock:
            if self.state != "idle":
                return self
            self.state = "starting"
            self.generation = 1
            self._gen_dirs[1] = self.model_dir
        for _ in range(self._initial_workers):
            self._spawn(1)
        self._router = _RouterFront(self, self._host, self._port).start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="mxtpu-fleet-monitor")
        self._monitor.start()
        _flight.rec("fleet.start", self.name,
                    f"{self._initial_workers} worker(s) @ {self.url}")
        if wait_ready:
            self.wait_ready(timeout=timeout)
        with self._lock:
            if self.state == "starting":
                self.state = "serving"
        self._write_summary(force=True)
        return self

    @property
    def url(self):
        return self._router.url if self._router is not None else None

    def wait_ready(self, timeout=None, generation=None):
        """Block until every desired worker of `generation` (default:
        the active one) passes the health gate; FleetError on timeout."""
        deadline = time.monotonic() + (self.cfg["ready_timeout"]
                                       if timeout is None else timeout)
        while True:
            gen = self.generation if generation is None else generation
            want = [s for s, g in self._desired.items() if g == gen]
            ready = self._gated_ready(want)
            if want and len(ready) == len(want):
                # publish to the router NOW — the monitor's next pass
                # may be a poll period away and the caller is about to
                # send traffic
                self._refresh()
                return ready
            if time.monotonic() >= deadline:
                anns = _worker.read_workers(self.run_dir)
                states = {s: (anns.get(s) or {}).get("state", "absent")
                          for s in want}
                raise FleetError(
                    f"fleet workers not ready within the deadline: "
                    f"{states}; supervisor: "
                    f"{ {s: r['state'] for s, r in self._sup.census().items()} }")
            time.sleep(0.05)

    def stop(self, drain=True):
        """Retire every worker (graceful drain by default), stop the
        router + monitor, write the final summary."""
        with self._lock:
            if self.state in ("stopped", "idle"):
                self.state = "stopped"
                return
            self.state = "stopping"
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        self._sup.stop_all(graceful=drain)
        if self._router is not None:
            self._router.close()
        with self._lock:
            self.state = "stopped"
            self._routable = []  # _desired kept: the final fleet.json
            # census is the diagnose report's post-mortem view
        _flight.rec("fleet.stop", self.name)
        self._write_summary(force=True)

    # ------------------------------------------------------------ routing --
    def _gated_ready(self, slots):
        """Slots (of the given census) passing the announce health gate
        with a live, pid-matching process. An ssh-placed slot relaxes
        the pid equality (the announce carries the REMOTE worker pid,
        the census the local ssh client's) — generation match + a live
        supervised process still gate it."""
        anns = _worker.read_workers(self.run_dir)
        census = self._sup.census()
        out = []
        for slot in slots:
            rec = census.get(slot)
            ann = anns.get(slot)
            pid_ok = ann is not None and rec is not None and (
                ann.get("pid") == rec.get("pid")
                or self._slot_ssh(slot) is not None)
            if (rec and rec.get("alive") and gate_ready(ann) and pid_ok
                    and ann.get("generation") == rec.get("generation")):
                out.append(slot)
                self._endpoints[slot] = (ann.get("host", "127.0.0.1"),
                                         int(ann["port"]))
        return out

    def _refresh(self):
        gen = self.generation
        want = sorted(s for s, g in self._desired.items() if g == gen)
        ready = self._gated_ready(want)
        now = time.monotonic()
        self._suspect = {s: t for s, t in self._suspect.items() if t > now}
        routable = [s for s in ready if s not in self._suspect]
        self._routable = routable or ready
        if self.cfg["policy"] == "hash":
            self._ring.rebuild(self._routable)

    def pick(self, model):
        """Policy-ordered candidate slots for one request: the routing
        policy (locality-aware when multi-host) orders them, then
        flagged persistent stragglers are stable-moved to the tail —
        they stay reachable (failover, hedges) but stop being anyone's
        first choice."""
        self._rr += 1
        depths = None
        if self.cfg["policy"] == "least_loaded":
            depths = {s: m.get("queue_depth")
                      for s, m in self._last_sample.get(
                          "per_worker", {}).items()}
        localities, penalty = None, 0.0
        if self.hosts is not None:
            localities = {s: self._slot_locality(s)
                          for s in self._routable}
            if any(v == "remote" for v in localities.values()):
                penalty = self._hedge.remote_penalty()
            else:
                localities = None
        order = order_candidates(self.cfg["policy"], model,
                                 self._routable, depths=depths,
                                 rr=self._rr, ring=self._ring,
                                 localities=localities,
                                 remote_penalty=penalty)
        return self._hedge.reorder(order, self._rr)

    def endpoint(self, slot):
        return self._endpoints.get(slot)

    # ------------------------------------------------- latency + hedging --
    def note_latency(self, slot, ms):
        """One completed router request against `slot` took `ms`
        end-to-end: feeds the hedge-threshold p99 ring, the per-slot
        straggler EWMAs and the per-locality spill penalty."""
        self._hedge.note(slot, ms)

    def hedge_plan(self, slot, candidates):
        """(hedge slot, threshold ms) for a first attempt against
        `slot`, or (None, None) — see :meth:`HedgeGovernor.plan`."""
        return self._hedge.plan(slot, candidates, self.endpoint)

    def _count_hedge(self, outcome):
        self._hedge.count(outcome)

    def mark_suspect(self, slot, why=""):
        """A connection-level failure against `slot`: deprioritize it
        until the monitor re-verifies (or the supervisor respawns it)."""
        self._suspect[slot] = time.monotonic() + 1.0
        self._routable = [s for s in self._routable if s != slot]
        _flight.rec("fleet.suspect", f"slot{slot}", why)

    def _count(self, key, n=1):
        with self._count_lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def models(self):
        """The served model list (from any ready worker's announce)."""
        anns = _worker.read_workers(self.run_dir)
        for slot in self._routable:
            ann = anns.get(slot)
            if ann and ann.get("models"):
                return {"models": ann["models"],
                        "generation": ann.get("generation")}
        return {"models": [], "generation": self.generation}

    # ------------------------------------------------------------ scaling --
    def scale_to(self, n, reason="manual"):
        """Grow/shrink the active generation to `n` workers (scale-up
        spawns; scale-down drains the highest slots through exit 75)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"fleet cannot scale below 1 worker (got {n})")
        with self._lock:
            gen = self.generation
            active = sorted(s for s, g in self._desired.items()
                            if g == gen)
            if n > len(active):
                added = [self._spawn(gen) for _ in range(n - len(active))]
                _flight.rec("fleet.scale", "up",
                            f"{len(active)} -> {n} ({reason})")
                _logger.info("fleet: scale up %d -> %d (%s; slots %s)",
                             len(active), n, reason, added)
            elif n < len(active):
                dropped = active[n:]
                for slot in dropped:
                    self._desired.pop(slot, None)
                    self._sup.drain_slot(slot, reason=f"scale-down "
                                                      f"({reason})")
                _flight.rec("fleet.scale", "down",
                            f"{len(active)} -> {n} ({reason})")
                _logger.info("fleet: scale down %d -> %d (%s; drained "
                             "%s)", len(active), n, reason, dropped)
        self._write_summary(force=True)
        return n

    def _sample(self, now):
        gen = self.generation
        active = {s for s, g in self._desired.items() if g == gen}
        per = worker_metrics(self.run_dir, slots=active)
        per = {s: m for s, m in per.items()
               if m.get("generation") == gen}
        depths = [m["queue_depth"] for m in per.values()
                  if m.get("queue_depth") is not None]
        p99s = [m["p99_ms"] for m in per.values()
                if m.get("p99_ms") is not None]
        fills = [m["fill"] for m in per.values()
                 if m.get("fill") is not None]
        completed = sum(m.get("completed") or 0.0 for m in per.values())
        rps = None
        if self._last_completed is not None:
            t0, c0 = self._last_completed
            dt = now - t0
            if dt > 0:
                rps = max(0.0, (completed - c0) / dt)
        self._last_completed = (now, completed)
        sample = {"queue_depth": max(depths) if depths else None,
                  "p99_ms": max(p99s) if p99s else None,
                  "fill": max(fills) if fills else None,
                  "rps": rps, "completed": completed,
                  "per_worker": per}
        self._last_sample = sample
        return sample

    def _autoscale_tick(self, now):
        sample = self._sample(now)
        # straggler flags ride the same cadence (one streak advance per
        # interval), autoscaling enabled or not — the router's hedge
        # threshold and candidate ordering depend on them either way
        self._hedge.update_stragglers(self._routable)
        if self.cfg["max"] <= self.cfg["min"]:
            return  # fixed-size fleet: sampling still feeds the router
        if self.state != "serving":
            return
        with self._lock:
            active = sum(1 for g in self._desired.values()
                         if g == self.generation)
        direction, rec = self._scaler.decide(sample, active, now=now)
        if direction == "up":
            self.scale_to(min(self.cfg["max"], active + 1),
                          reason=f"autoscale: {rec['reason']}")
        elif direction == "down":
            self.scale_to(max(self.cfg["min"], active - 1),
                          reason=f"autoscale: {rec['reason']}")
        if direction:
            _flight.rec("fleet.autoscale", direction, rec["reason"])

    # ------------------------------------------------------------ rollout --
    def rollout(self, new_model_dir, timeout=None):
        """Zero-downtime model swap: spawn a generation-N+1 worker set
        from `new_model_dir` (warm from the shared disk compile cache),
        health-gate every new worker (announce census with zero pending
        compiles + live ``/healthz``), shift router traffic atomically,
        then drain generation N through exit 75. Returns the rollout
        record; raises :class:`FleetError` (old generation untouched)
        when the gate times out."""
        import urllib.request

        with self._lock:
            if self.state != "serving":
                raise FleetError(
                    f"rollout needs a serving fleet (state "
                    f"{self.state!r})")
            old_gen = self.generation
            new_gen = old_gen + 1
            self._gen_dirs[new_gen] = os.fspath(new_model_dir)
            old_slots = sorted(s for s, g in self._desired.items()
                               if g == old_gen)
            n = max(1, len(old_slots))
            # the autoscaler sits out the swap (state-gated): a census
            # change mid-rollout would race the generation accounting
            self.state = "rolling-out"
        rec = {"generation": new_gen,
               "model_dir": os.fspath(new_model_dir),
               "from_generation": old_gen, "t_start": time.time(),
               "workers": [], "drained": {}, "state": "spawning"}
        _flight.rec("fleet.rollout", f"gen{new_gen}",
                    os.fspath(new_model_dir))
        _logger.info("fleet: rollout -> generation %d (%s), %d worker(s)",
                     new_gen, new_model_dir, n)
        new_slots = [self._spawn(new_gen) for _ in range(n)]
        rec["workers"] = new_slots
        # ---- health gate: announce-ready + zero pending compiles + a
        # live /healthz answer from every new worker
        deadline = time.monotonic() + (self.cfg["ready_timeout"]
                                       if timeout is None else timeout)
        rec["state"] = "health-gate"
        while True:
            ready = self._gated_ready(new_slots)
            if len(ready) == len(new_slots):
                healthy = []
                for slot in ready:
                    host, port = self._endpoints[slot]
                    try:
                        with urllib.request.urlopen(
                                f"http://{host}:{port}/healthz",
                                timeout=2.0) as resp:
                            ok = json.loads(resp.read()).get(
                                "status") == "ok"
                    except (OSError, ValueError):
                        ok = False
                    if ok:
                        healthy.append(slot)
                if len(healthy) == len(new_slots):
                    break
            if time.monotonic() >= deadline:
                anns = _worker.read_workers(self.run_dir)
                states = {
                    s: {"state": (anns.get(s) or {}).get("state",
                                                         "absent"),
                        "pending_compiles":
                        (anns.get(s) or {}).get("pending_compiles")}
                    for s in new_slots}
                with self._lock:
                    for slot in new_slots:
                        self._desired.pop(slot, None)
                        self._sup.drain_slot(slot,
                                             reason="rollout aborted")
                rec["state"] = "aborted"
                rec["gate_failures"] = states
                self.rollouts.append(rec)
                with self._lock:
                    self.generation = old_gen
                    self._gen_dirs.pop(new_gen, None)
                    self.state = "serving"
                self._write_summary(force=True)
                raise FleetError(
                    f"rollout to generation {new_gen} aborted: health "
                    f"gate not passed within the deadline — {states} "
                    "(the old generation keeps serving)")
            time.sleep(0.05)
        # ---- atomic traffic shift, then drain the old generation
        with self._lock:
            self.generation = new_gen
        self._refresh()
        rec["state"] = "draining-old"
        rec["t_shift"] = time.time()
        _flight.rec("fleet.shift", f"gen{new_gen}",
                    f"{len(new_slots)} worker(s) live")
        with self._lock:
            for slot in old_slots:
                self._desired.pop(slot, None)
                self._sup.drain_slot(slot,
                                     reason=f"rollout gen{new_gen}")
        drain_deadline = time.monotonic() + self.cfg["drain_timeout"]
        while time.monotonic() < drain_deadline:
            self._sup.poll()
            left = [s for s in old_slots if s in self._sup.slots]
            if not left:
                break
            time.sleep(0.05)
        for ev in self._sup.events:
            if ev["kind"] in ("drained", "drain_killed") \
                    and ev["slot"] in old_slots:
                rec["drained"][str(ev["slot"])] = ev.get("exit_code")
        anns = _worker.read_workers(self.run_dir)
        rec["old_final"] = {
            str(s): {k: (anns.get(s) or {}).get(k)
                     for k in ("state", "admitted", "answered", "failed",
                               "drained")}
            for s in old_slots}
        rec["state"] = "done"
        rec["t_done"] = time.time()
        self.rollouts.append(rec)
        with self._lock:
            self.state = "serving"
        _logger.info("fleet: rollout to generation %d complete (old "
                     "generation exits: %s)", new_gen, rec["drained"])
        self._write_summary(force=True)
        return rec

    # ------------------------------------------------------------ monitor --
    def _monitor_loop(self):
        next_tick = 0.0
        while not self._stop_evt.is_set():
            try:
                self._sup.poll()
                self._refresh()
                now = time.monotonic()
                if now >= next_tick:
                    next_tick = now + self.cfg["interval"]
                    self._autoscale_tick(now)
                self._write_summary()
            except Exception:
                _logger.exception("fleet: monitor pass failed (fleet "
                                  "keeps serving)")
            self._stop_evt.wait(0.05)

    # -------------------------------------------------------------- state --
    def stats(self, light=False):
        """The fleet's aggregate observability snapshot (router /stats,
        fleet.json, diagnose)."""
        with self._lock:
            desired = dict(self._desired)
            gen = self.generation
        base = {"name": self.name, "state": self.state,
                "generation": gen, "policy": self.cfg["policy"],
                "desired": sum(1 for g in desired.values() if g == gen),
                "ready": len(self._routable)}
        if light:
            return base
        census = self._sup.census()
        anns = _worker.read_workers(self.run_dir)
        per = self._last_sample.get("per_worker", {})
        workers = {}
        for slot, g in sorted(desired.items()):
            rec = census.get(slot) or {}
            ann = anns.get(slot) or {}
            m = per.get(slot) or {}
            workers[str(slot)] = {
                "generation": g, "state": rec.get("state"),
                "alive": rec.get("alive"), "pid": rec.get("pid"),
                "restarts": rec.get("restarts"),
                "port": ann.get("port"), "ready": gate_ready(ann),
                "models": ann.get("models"),
                "queue_depth": m.get("queue_depth"),
                "p99_ms": m.get("p99_ms"), "rps": m.get("rps"),
                "shard_age_s": m.get("age_s"),
                "model_bus": ann.get("model_bus"),
                "host": self._slot_host.get(slot),
                "locality": self._slot_locality(slot),
                "straggler": slot in self._hedge.stragglers}
        with self._count_lock:
            counters = dict(self._counters)
        hedge_state = self._hedge.describe()
        base.update({
            "url": self.url, "run_dir": self.run_dir,
            "bus_dir": self.bus_dir,
            "uptime_s": round(time.monotonic() - self._t_start, 1),
            "workers": workers,
            "hosts": None if self.hosts is None else [
                {"name": h["name"], "ssh": h["ssh"],
                 "locality": h["locality"],
                 "advertise": h["advertise"],
                 "slots": sorted(
                     s for s, hn in self._slot_host.items()
                     if hn == h["name"] and s in desired)}
                for h in self.hosts],
            "router": counters,
            "hedges": hedge_state["hedges"],
            "stragglers": hedge_state["stragglers"],
            "router_latency": hedge_state["router_latency"],
            "autoscaler": self._scaler.describe(),
            "sample": {k: self._last_sample.get(k) for k in
                       ("queue_depth", "p99_ms", "fill", "rps")},
            "rollouts": [
                {k: v for k, v in r.items() if k != "old_final"}
                for r in self.rollouts[-8:]],
            "supervisor": {"restarts_total": self._sup.restarts_total,
                           "drained_total": self._sup.drained_total},
        })
        return base

    def describe(self):
        """stats() + config + supervisor events (fleet.json)."""
        out = self.stats()
        out["config"] = dict(self.cfg)
        out["events"] = list(self._sup.events[-64:])
        return out

    def _write_summary(self, force=False):
        now = time.monotonic()
        if not force and now - self._summary_at < 1.0:
            return
        self._summary_at = now
        from .. import elastic as _elastic

        try:
            rec = self.describe()
            rec["updated"] = time.time()
            _elastic._atomic_json(
                os.path.join(self.run_dir, "fleet.json"), rec)
        except OSError as e:
            _logger.warning("fleet: could not write fleet.json: %s", e)


# --------------------------------------------------- telemetry collector ---

def _collect_serving_fleet():
    """Scrape-time gauges for the most recent live fleet in this
    process: rollout generation, census, fleet-wide completion rate and
    the router/autoscale counters (the per-worker gauge re-exports come
    from :mod:`mxnet_tpu.telemetry.fleet`'s shard collector)."""
    from ..telemetry import registry as _registry

    fleets = sorted(_LIVE, key=lambda f: f._t_start)
    if not fleets:
        return
    fl = fleets[-1]
    st = fl.stats(light=True)
    _registry.gauge("mxtpu_fleet_generation",
                    "Active fleet model generation (bumps per rollout)"
                    ).set(st["generation"])
    _registry.gauge("mxtpu_fleet_workers_desired",
                    "Workers the fleet wants in the active generation"
                    ).set(st["desired"])
    _registry.gauge("mxtpu_fleet_workers_ready",
                    "Workers currently routable").set(st["ready"])
    rps = fl._last_sample.get("rps")
    _registry.gauge("mxtpu_fleet_rps",
                    "Fleet-wide completion rate over the last "
                    "autoscaler interval").set(rps or 0.0)
    router = _registry.counter("mxtpu_fleet_router_requests_total",
                               "Router requests by outcome",
                               labels=("outcome",))
    with fl._count_lock:
        counters = dict(fl._counters)
    for outcome, n in counters.items():
        router.set_total(n, outcome)
    hedge = _registry.counter(
        "mxtpu_fleet_hedges_total",
        "Hedged router requests by outcome (fired/won/lost/failed)",
        labels=("outcome",))
    with fl._hedge._lock:
        hedges = dict(fl._hedge.counters)
    for outcome, n in hedges.items():
        hedge.set_total(n, outcome)
    _registry.gauge("mxtpu_fleet_stragglers",
                    "Worker slots currently flagged as persistent "
                    "router-latency stragglers").set(
                        len(fl._hedge.stragglers))
    scale = _registry.counter("mxtpu_fleet_autoscale_total",
                              "Autoscaler actions", labels=("direction",))
    for direction, n in fl._scaler.decisions.items():
        scale.set_total(n, direction)
    _registry.counter("mxtpu_fleet_worker_restarts_total",
                      "Fleet worker slot restarts").set_total(
                          fl._sup.restarts_total)
    _registry.counter("mxtpu_fleet_workers_drained_total",
                      "Deliberately drained fleet workers (rollout / "
                      "scale-down / stop)").set_total(
                          fl._sup.drained_total)


def _install_collector():
    global _collector_installed
    if _collector_installed:
        return
    _collector_installed = True
    from ..telemetry import export as _export

    _export.register_collector("serving_fleet", _collect_serving_fleet)
