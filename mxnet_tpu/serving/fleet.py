"""ServingFleet: N ModelServer workers behind one router front door.

The ps-lite scheduler/server split (SURVEY §L7) replayed for inference:
one `ModelServer` process sustains thousands of req/s (PR 8), "millions
of users" needs N of them behind one address. Everything here composes
pieces the stack already has:

* **process plane** — a serving-mode supervisor
  (:class:`mxnet_tpu.elastic.ServingSupervisor`): per-slot restart with
  backoff, heartbeat liveness kills, the exit-code ladder; exit 75 on a
  deliberately drained slot retires it (rollout / scale-down) instead of
  restarting;
* **router** — an HTTP front end dispatching ``POST
  /v1/models/<m>:predict`` to workers over persistent (keep-alive)
  upstream connections. Placement: **least-loaded** (live queue depth
  from each worker's telemetry shard, falling back to round-robin when
  shards are missing/stale), **consistent-hash-by-model** (a vnode hash
  ring — a worker-set change only remaps the keys the lost worker
  owned), or plain round-robin. A connection-refused/reset upstream (a
  dying worker) is retried on a different worker — a request is only
  ever lost if NO worker can take it — and a worker's 503
  (draining/not-admitted) fails over the same way. Upstream timeouts are
  NOT retried: the batch may already be running;
* **autoscaler** — a control loop over the gauges telemetry already
  exports per worker (queue depth / p99 / batch fill / completion rate):
  sustained pressure for K samples scales up, sustained idle scales
  down, min/max bounds and a cooldown damp oscillation
  (``MXNET_TPU_FLEET`` grammar below);
* **zero-downtime rollout** — :meth:`ServingFleet.rollout` starts a
  generation-N+1 worker set from ``new_model_dir`` (warming from the
  persistent compile cache: a warm generation LOADS, never compiles),
  health-gates every new worker (``/healthz`` + an announce census
  showing ZERO pending compiles), shifts router traffic atomically,
  then drains generation N through the exit-75 protocol — mid-load,
  with zero dropped admitted requests.

``MXNET_TPU_FLEET`` env grammar (mirrors FAULTS/WATCHDOG: one variable,
``,``/``;``-separated ``option:value`` entries; constructor kwargs and
``config=`` override)::

    min:<N>            autoscaler lower bound (default 1)
    max:<N>            autoscaler upper bound (default 4; min==max
                       disables autoscaling)
    up_queue:<N>       scale-up pressure: any worker's queue depth >= N
                       (default 32)
    up_p99_ms:<F>      scale-up pressure: any worker's p99 >= F (250)
    up_fill:<F>        scale-up pressure: batch fill ratio >= F (0.98 —
                       full buckets mean the batcher is saturated)
    k:<N>              consecutive pressure samples before scaling up (3)
    idle_rps:<F>       scale-down: fleet completion rate <= F req/s with
                       empty queues (default 1.0)
    idle_k:<N>         consecutive idle samples before scaling down (5)
    cooldown:<F>       seconds after any scale action before the next (10)
    interval:<F>       autoscaler sampling period, seconds (1.0)
    policy:<P>         least_loaded | hash | round_robin (least_loaded)
    beat:<F>           worker heartbeat/telemetry-shard cadence (0.5)
    ready_timeout:<F>  worker-ready / rollout health-gate deadline (120)
    drain_timeout:<F>  generation drain deadline during rollout (60)
    grace:<F>          drain SIGTERM->SIGKILL escalation deadline (15)
    dead_after:<F>     heartbeat-silence kill threshold (30; 0 off)
    restarts:<N>       per-slot restart budget (5)
    timeout_ms:<F>     router upstream request deadline (30000)

Quick start::

    from mxnet_tpu.serving import fleet, worker

    worker.write_spec(model_dir, worker.demo_spec(models=2))
    f = fleet.ServingFleet(model_dir, workers=2).start()
    ...                           # drive f.url like any serving front end
    f.rollout(new_model_dir)      # zero-downtime model swap
    f.stop()

Observability: ``fleet.json`` in the run dir (census, autoscaler state,
rollout history, router counters — the diagnose "Serving Fleet" report),
``mxtpu_fleet_*`` gauges on the router's ``/metrics`` (generation,
ready/desired workers, fleet rps, router/autoscale counters, plus the
per-rank re-exports from :mod:`mxnet_tpu.telemetry.fleet`), and
``fleet.*`` flight events for every lifecycle transition.
"""
from __future__ import annotations

import hashlib
import http.client
import json
import os
import re
import socket
import sys
import threading
import time
import weakref

from .. import log as _log
from ..telemetry import flight as _flight
from . import worker as _worker
from .errors import ServingError

__all__ = ["ServingFleet", "FleetError", "Autoscaler", "HashRing",
           "order_candidates", "gate_ready", "worker_metrics",
           "configure", "effective", "describe", "live_fleets",
           "DEFAULTS", "ENV", "POLICIES"]

_logger = _log.get_logger("mxnet_tpu.serving.fleet")

ENV = "MXNET_TPU_FLEET"

POLICIES = ("least_loaded", "hash", "round_robin")

DEFAULTS = {
    "min": 1,
    "max": 4,
    "up_queue": 32,
    "up_p99_ms": 250.0,
    "up_fill": 0.98,
    "k": 3,
    "idle_rps": 1.0,
    "idle_k": 5,
    "cooldown": 10.0,
    "interval": 1.0,
    "policy": "least_loaded",
    "beat": 0.5,
    "ready_timeout": 120.0,
    "drain_timeout": 60.0,
    "grace": 15.0,
    "dead_after": 30.0,
    "restarts": 5,
    "timeout_ms": 30000.0,
}

_INT_KEYS = ("min", "max", "up_queue", "k", "idle_k", "restarts")
_FLOAT_KEYS = ("up_p99_ms", "up_fill", "idle_rps", "cooldown", "interval",
               "beat", "ready_timeout", "drain_timeout", "grace",
               "dead_after", "timeout_ms")

_cfg_lock = threading.Lock()
_CFG: dict | None = None
_loaded_env = False


class FleetError(ServingError):
    """Fleet-level failure: workers never became ready, a rollout's
    health gate timed out, or the fleet was asked to serve with no
    routable workers."""


def _coerce(key, val):
    if key == "policy":
        v = str(val).strip().lower()
        if v not in POLICIES:
            raise ValueError(f"unknown fleet policy {val!r}; expected one "
                             f"of {POLICIES}")
        return v
    if key in _INT_KEYS:
        n = int(val)
        if n < 0 or (n < 1 and key in ("min", "max")):
            raise ValueError(f"fleet {key} must be >= 1, got {n}")
        return n
    if key in _FLOAT_KEYS:
        f = float(val)
        if f < 0:
            raise ValueError(f"fleet {key} must be >= 0, got {f}")
        return f
    raise ValueError(f"unknown fleet option {key!r}; expected one of "
                     f"{sorted(DEFAULTS)}")


def _parse(spec):
    cfg = dict(DEFAULTS)
    for entry in re.split(r"[;,]", spec):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, val = entry.partition(":")
        key, val = key.strip(), val.strip()
        if not sep or not val:
            raise ValueError(
                f"bad {ENV} entry {entry!r}: expected <option>:<value>")
        cfg[key] = _coerce(key, val)
    if cfg["max"] < cfg["min"]:
        raise ValueError(f"fleet max ({cfg['max']}) < min ({cfg['min']})")
    return cfg


def configure(spec=None, **options):
    """Install a fleet configuration (grammar string, dict, or kwargs on
    top of the defaults); pass nothing to reset to env/defaults."""
    global _CFG, _loaded_env
    if isinstance(spec, dict):
        cfg = dict(DEFAULTS)
        for k, v in spec.items():
            cfg[k] = _coerce(k, v)
    elif spec:
        cfg = _parse(spec)
    else:
        cfg = dict(DEFAULTS)
    for k, v in options.items():
        cfg[k] = _coerce(k, v)
    if cfg["max"] < cfg["min"]:
        raise ValueError(f"fleet max ({cfg['max']}) < min ({cfg['min']})")
    with _cfg_lock:
        _loaded_env = True
        _CFG = cfg
    return dict(cfg)


def _ensure_env():
    global _loaded_env, _CFG
    if _loaded_env:
        return
    with _cfg_lock:
        if _loaded_env:
            return
        _loaded_env = True
        env = os.environ.get(ENV, "")
        if env:
            try:
                _CFG = _parse(env)
            except ValueError as e:
                _logger.warning("ignoring invalid %s: %s", ENV, e)
                _CFG = None


def effective() -> dict:
    """The effective fleet configuration (env-seeded, configure-wins)."""
    _ensure_env()
    cfg = _CFG
    return dict(cfg) if cfg is not None else dict(DEFAULTS)


def describe() -> dict:
    """Knobs + provenance (tools/diagnose.py 'Serving Fleet')."""
    out = effective()
    out["env"] = os.environ.get(ENV, "<unset>")
    return out


# ------------------------------------------------------- routing policies --

def _hash32(s):
    return int(hashlib.md5(str(s).encode()).hexdigest()[:8], 16)


class HashRing:
    """Consistent hashing over worker slots (``vnodes`` points per slot):
    removing a worker only remaps the keys that worker owned; the other
    keys keep their placement — the property the fleet's
    consistent-hash-by-model policy needs across worker churn."""

    def __init__(self, slots=(), vnodes=64):
        self.vnodes = int(vnodes)
        self._ring = []            # sorted [(point, slot)]
        self.rebuild(slots)

    def rebuild(self, slots):
        self._ring = sorted(
            (_hash32(f"{slot}:{v}"), slot)
            for slot in set(slots) for v in range(self.vnodes))
        return self

    def lookup(self, key, allowed=None):
        """The slot owning `key` (restricted to `allowed` when given);
        None on an empty ring."""
        ring = self._ring
        if not ring:
            return None
        h = _hash32(key)
        lo, hi = 0, len(ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        for i in range(len(ring)):
            slot = ring[(lo + i) % len(ring)][1]
            if allowed is None or slot in allowed:
                return slot
        return None


def order_candidates(policy, model, slots, depths=None, rr=0, ring=None):
    """Order the routable `slots` for one request: the head is the
    placement choice, the tail is the failover order.

    * ``least_loaded`` — ascending live queue depth (unknown depth
      counts as 0: a fresh worker has an empty queue), round-robin
      rotation breaking ties; with NO depth known at all this degrades
      to pure round-robin.
    * ``hash`` — the consistent-hash owner of `model` first, the rest
      rotated.
    * ``round_robin`` — rotation by the request counter.
    """
    slots = list(slots)
    if not slots:
        return []
    k = rr % len(slots)
    rotated = slots[k:] + slots[:k]
    if policy == "hash" and ring is not None:
        primary = ring.lookup(model, allowed=set(slots))
        if primary is None:
            return rotated
        return [primary] + [s for s in rotated if s != primary]
    if policy == "least_loaded" and depths \
            and any(depths.get(s) is not None for s in slots):
        return sorted(rotated, key=lambda s: depths.get(s) or 0)
    return rotated


def gate_ready(announce):
    """The rollout health gate's announce half: a worker may take
    traffic only when it announced ``serving`` + ``ready`` with ZERO
    pending compiles (an unwarmed ladder would recompile under traffic —
    exactly what a rollout must never do)."""
    return (bool(announce)
            and announce.get("state") == "serving"
            and bool(announce.get("ready"))
            and int(announce.get("pending_compiles") or 0) == 0)


# ---------------------------------------------------------- shard reading --

def _series_values(shard, name, **match):
    out = []
    metric = (shard.get("metrics") or {}).get(name)
    if not isinstance(metric, dict):
        return out
    for series in metric.get("series") or ():
        labels = series.get("labels") or {}
        if all(labels.get(k) == v for k, v in match.items()):
            v = series.get("value")
            if isinstance(v, (int, float)):
                out.append(float(v))
    return out


def worker_metrics(run_dir, slots=None):
    """Per-worker serving gauges from the telemetry shards each worker
    co-writes with its heartbeat: ``{slot: {queue_depth, p99_ms, fill,
    completed, rps, age_s, generation}}``. Missing/torn shards are
    simply absent — callers fall back (router: round-robin; autoscaler:
    no pressure signal from that worker)."""
    from ..telemetry import fleet as _tfleet

    out = {}
    now = time.time()
    for rank, shard in _tfleet.read_shards(run_dir).items():
        if slots is not None and rank not in slots:
            continue
        depth = _series_values(shard, "mxtpu_serving_queue_depth")
        p99 = _series_values(shard, "mxtpu_serving_latency_ms",
                             quantile="p99")
        fill = _series_values(shard, "mxtpu_serving_batch_fill_ratio")
        done = _series_values(shard, "mxtpu_serving_requests_total",
                              outcome="completed")
        rps = _series_values(shard, "mxtpu_serving_rps")
        out[rank] = {
            "queue_depth": sum(depth) if depth else None,
            "p99_ms": max(p99) if p99 else None,
            "fill": max(fill) if fill else None,
            "completed": sum(done) if done else 0.0,
            "rps": sum(rps) if rps else None,
            "age_s": round(now - float(shard.get("t_wall", now)), 3),
            "generation": shard.get("generation"),
        }
    return out


# -------------------------------------------------------------- autoscaler --

class Autoscaler:
    """The scaling decision core, pure enough to table-test: feed it one
    aggregate sample per interval and it answers up/down/None.

    Pressure (any of: max queue depth >= ``up_queue``, max p99 >=
    ``up_p99_ms``, max batch fill >= ``up_fill``) sustained for ``k``
    consecutive samples scales up; idleness (completion rate <=
    ``idle_rps`` AND empty queues) sustained for ``idle_k`` samples
    scales down; every action starts a ``cooldown`` window during which
    streaks keep accumulating but nothing fires; ``min``/``max`` bound
    the census."""

    def __init__(self, cfg=None):
        self.cfg = dict(effective() if cfg is None else cfg)
        self.up_streak = 0
        self.idle_streak = 0
        self.cooldown_until = 0.0
        self.last = None           # last decision record (incl. holds)
        self.last_action = None    # last actual up/down
        self.decisions = {"up": 0, "down": 0}

    def decide(self, sample, workers, now=None):
        """One sample -> ("up"|"down"|None, record). `sample` carries
        ``queue_depth``/``p99_ms``/``fill`` (fleet-max) + ``rps``
        (fleet completion rate); `workers` is the current census."""
        cfg = self.cfg
        now = time.monotonic() if now is None else now
        pressure = []
        q = sample.get("queue_depth")
        if q is not None and q >= cfg["up_queue"]:
            pressure.append(f"queue {q:g} >= {cfg['up_queue']}")
        p99 = sample.get("p99_ms")
        if p99 is not None and p99 >= cfg["up_p99_ms"]:
            pressure.append(f"p99 {p99:g}ms >= {cfg['up_p99_ms']:g}")
        fill = sample.get("fill")
        if fill is not None and fill >= cfg["up_fill"]:
            pressure.append(f"fill {fill:g} >= {cfg['up_fill']:g}")
        rps = sample.get("rps")
        # idleness takes PRECEDENCE over pressure: p99/fill are
        # recent-window gauges that stay high after traffic stops — an
        # empty-queue fleet completing nothing is idle no matter what
        # its stale latency gauges say
        idle = (rps is not None and rps <= cfg["idle_rps"] and not q)
        if idle:
            self.idle_streak += 1
            self.up_streak = 0
        elif pressure:
            self.up_streak += 1
            self.idle_streak = 0
        else:
            self.up_streak = 0
            self.idle_streak = 0
        direction, why = None, None
        if self.up_streak >= cfg["k"]:
            if workers >= cfg["max"]:
                why = f"at max ({cfg['max']})"
            elif now < self.cooldown_until:
                why = "cooling down"
            else:
                direction = "up"
                why = "; ".join(pressure)
        elif self.idle_streak >= cfg["idle_k"]:
            if workers <= cfg["min"]:
                why = f"at min ({cfg['min']})"
            elif now < self.cooldown_until:
                why = "cooling down"
            else:
                direction = "down"
                why = (f"idle: rps {rps:g} <= {cfg['idle_rps']:g} for "
                       f"{self.idle_streak} samples")
        rec = {"t_wall": time.time(), "direction": direction,
               "reason": why, "workers": workers,
               "up_streak": self.up_streak,
               "idle_streak": self.idle_streak,
               "sample": {k: sample.get(k) for k in
                          ("queue_depth", "p99_ms", "fill", "rps")}}
        self.last = rec
        if direction is not None:
            self.cooldown_until = now + cfg["cooldown"]
            self.up_streak = 0
            self.idle_streak = 0
            self.decisions[direction] += 1
            self.last_action = rec
        return direction, rec

    def describe(self):
        return {"last": self.last, "last_action": self.last_action,
                "decisions": dict(self.decisions),
                "up_streak": self.up_streak,
                "idle_streak": self.idle_streak,
                "enabled": self.cfg["max"] > self.cfg["min"]}


# ------------------------------------------------------------- the router --

_PREDICT_RE = re.compile(r"^/(?:v1/models|models|predict)/([^/:]+)"
                         r"(?::predict)?$")

#: upstream failures safe to retry on ANOTHER worker: the connection
#: died before (or instead of) a response — the request was never
#: admitted there. A timeout is NOT in this set: the batch may already
#: be running, and "zero dropped admitted requests" forbids guessing.
_RETRYABLE = (ConnectionError, http.client.HTTPException,
              socket.gaierror)


class _RouterFront:
    """The fleet's HTTP front door: proxies predict traffic to workers
    over persistent per-thread upstream connections, retrying
    connection-level failures (and worker 503s — not-admitted by
    construction) on the next candidate."""

    def __init__(self, fleet, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        self._fleet = fleet
        self._local = threading.local()
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "mxtpu-fleet/0.1"
            # keep-alive + separate header/body sends otherwise hit the
            # Nagle x delayed-ACK 40ms stall — even on loopback
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass

            def _json(self, code, payload, extra_headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                fl = front._fleet
                if self.path == "/healthz":
                    st = fl.stats(light=True)
                    ok = st["ready"] >= 1
                    self._json(200 if ok else 503,
                               {"status": "ok" if ok else "degraded",
                                "generation": st["generation"],
                                "workers_ready": st["ready"],
                                "workers_desired": st["desired"]})
                elif self.path in ("/v1/models", "/models"):
                    self._json(200, fl.models())
                elif self.path in ("/v1/stats", "/stats"):
                    self._json(200, fl.stats())
                elif self.path == "/metrics":
                    from ..telemetry import export as _export

                    body = _export.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     _export.PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics.json":
                    from ..telemetry import export as _export

                    body = _export.render_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": f"no route {self.path!r}"})

            def do_POST(self):
                m = _PREDICT_RE.match(self.path)
                if not m:
                    self._json(404, {"error": f"no route {self.path!r}"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                rid = self.headers.get("X-Request-Id")
                if not rid:
                    from ..telemetry import trace as _trace

                    rid = _trace.new_request_id()
                status, payload, hdrs = front._dispatch(
                    m.group(1), self.path, body,
                    self.headers.get("Content-Type", "application/json"),
                    rid)
                self.send_response(status)
                for k, v in hdrs:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = None

    # ------------------------------------------------------- dispatching --
    def _conn_to(self, slot, endpoint):
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn, ep = conns.get(slot, (None, None))
        if conn is None or ep != endpoint:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            conn = http.client.HTTPConnection(
                endpoint[0], endpoint[1],
                timeout=self._fleet.cfg["timeout_ms"] / 1e3)
            conn.connect()
            # persistent upstream: TCP_NODELAY or every request eats the
            # Nagle x delayed-ACK stall
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                 1)
            conns[slot] = (conn, endpoint)
        return conn

    def _drop_conn(self, slot):
        conns = getattr(self._local, "conns", None)
        if conns:
            conn, _ = conns.pop(slot, (None, None))
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _dispatch(self, model, path, body, ctype, rid):
        """Route one admitted-at-the-front-door request: walk the
        policy-ordered candidates; connection-level failures and 503s
        fail over to the next worker; the LAST candidate's verdict (or a
        fleet 503) goes back to the client."""
        fleet = self._fleet
        fleet._count("requests")
        candidates = fleet.pick(model)
        rid_hdr = [("X-Request-Id", rid)]
        if not candidates:
            fleet._count("rejects")
            return 503, json.dumps(
                {"error": "no ready workers in the fleet",
                 "request_id": rid}).encode(), \
                rid_hdr + [("Content-Type", "application/json"),
                           ("Retry-After", "1")]
        last_err = None
        for attempt, slot in enumerate(candidates):
            endpoint = fleet.endpoint(slot)
            if endpoint is None:
                continue
            if attempt:
                fleet._count("retries")
            try:
                conn = self._conn_to(slot, endpoint)
                conn.request("POST", path, body=body,
                             headers={"Content-Type": ctype,
                                      "X-Request-Id": rid})
                resp = conn.getresponse()
                payload = resp.read()
            except socket.timeout:
                # maybe admitted: do NOT replay on another worker
                self._drop_conn(slot)
                fleet._count("errors")
                return 504, json.dumps(
                    {"error": f"worker {slot} timed out",
                     "request_id": rid}).encode(), \
                    rid_hdr + [("Content-Type", "application/json")]
            except _RETRYABLE + (OSError,) as e:
                self._drop_conn(slot)
                fleet.mark_suspect(slot, repr(e))
                last_err = f"{type(e).__name__}: {e}"
                continue
            if resp.status == 503 and attempt + 1 < len(candidates):
                # draining worker: the request was NOT admitted there
                continue
            if 200 <= resp.status < 300:
                fleet._count("completed")
            hdrs = rid_hdr + [("Content-Type",
                               resp.getheader("Content-Type",
                                              "application/json"))]
            if resp.status in (429, 503):
                hdrs.append(("Retry-After",
                             resp.getheader("Retry-After", "0.1")))
            return resp.status, payload, hdrs
        fleet._count("rejects")
        return 503, json.dumps(
            {"error": "every fleet worker refused the request",
             "last_error": last_err, "request_id": rid}).encode(), \
            rid_hdr + [("Content-Type", "application/json"),
                       ("Retry-After", "1")]

    # ---------------------------------------------------------- lifecycle --
    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1}, daemon=True,
                name="mxtpu-fleet-router")
            self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# --------------------------------------------------------------- the fleet --

_LIVE = weakref.WeakSet()
_collector_installed = False


def live_fleets():
    """ServingFleet instances alive in this process (diagnose)."""
    return list(_LIVE)


class ServingFleet:
    """Supervise N serving workers behind one router (docs/SERVING.md
    "Fleet"). The three control surfaces — per-slot supervision,
    telemetry-driven autoscaling, zero-downtime rollout — run on one
    monitor thread; the router serves on its own HTTP threads."""

    def __init__(self, model_dir, workers=None, *, run_dir=None,
                 policy=None, host="127.0.0.1", port=0, config=None,
                 warmup=True, env=None, cwd=None, name="fleet",
                 bus_dir=None, popen=None):
        import tempfile

        cfg = dict(effective())
        if isinstance(config, str):
            cfg.update(_parse(config))
        elif config:
            for k, v in config.items():
                cfg[k] = _coerce(k, v)
        if policy is not None:
            cfg["policy"] = _coerce("policy", policy)
        self.cfg = cfg
        self.name = str(name)
        self.model_dir = os.fspath(model_dir)
        self.run_dir = os.fspath(
            run_dir or tempfile.mkdtemp(prefix="mxtpu_fleet_"))
        os.makedirs(self.run_dir, exist_ok=True)
        self._initial_workers = max(1, int(cfg["min"]
                                           if workers is None else workers))
        self._host, self._port = host, int(port)
        self._warmup = bool(warmup)
        self.generation = 0
        self.state = "idle"
        self._gen_dirs = {}        # generation -> model dir
        self._desired = {}         # slot -> generation
        self._next_slot = 0
        self._routable = []        # slots taking traffic right now
        self._endpoints = {}       # slot -> (host, port)
        self._suspect = {}         # slot -> monotonic deadline
        self._rr = 0
        self._ring = HashRing()
        self.rollouts = []
        self._counters = {"requests": 0, "completed": 0, "retries": 0,
                          "rejects": 0, "errors": 0}
        self._count_lock = threading.Lock()
        self._scaler = Autoscaler(cfg)
        self._last_completed = None   # (t_mono, fleet completed total)
        self._last_sample = {}
        self._lock = threading.RLock()      # census + rollout/scale
        self._stop_evt = threading.Event()
        self._monitor = None
        self._router = None
        self._summary_at = 0.0

        worker_env = dict(env or {})
        worker_env.setdefault("MXNET_TPU_GANG_BEAT", str(cfg["beat"]))
        # workers must find this package without an installed dist
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        worker_env["PYTHONPATH"] = pkg_root + os.pathsep + \
            os.environ.get("PYTHONPATH", "")
        # a shared persistent compile cache is what makes rollout cheap:
        # generation N+1 LOADS the ladder the first generation compiled
        worker_env.setdefault("MXNET_TPU_CACHE_DIR",
                              os.environ.get("MXNET_TPU_CACHE_DIR")
                              or os.path.join(self.run_dir, "cache"))
        # diagnose run next to the fleet finds the run dir through this
        worker_env.setdefault("MXTPU_FLEET_DIR", self.run_dir)
        # live weight streaming: every worker of every generation
        # subscribes to the same bus (the trainer's publish_to target)
        self.bus_dir = os.fspath(bus_dir) if bus_dir \
            else os.environ.get("MXTPU_MODELBUS_DIR")
        if self.bus_dir:
            worker_env.setdefault("MXTPU_MODELBUS_DIR", self.bus_dir)

        from .. import elastic as _elastic

        self._sup = _elastic.ServingSupervisor(
            self._command_for, self.run_dir, grace=cfg["grace"],
            dead_after=cfg["dead_after"], max_restarts=cfg["restarts"],
            env=worker_env, cwd=cwd, popen=popen)

        from ..telemetry import fleet as _tfleet

        _tfleet.install(self.run_dir)
        _install_collector()
        _LIVE.add(self)
        self._t_start = time.monotonic()

    # -------------------------------------------------------- worker cmds --
    def _command_for(self, slot, generation):
        cmd = [sys.executable, "-m", "mxnet_tpu.serving.worker",
               "--model-dir", self._gen_dirs[generation],
               "--slot", str(slot), "--generation", str(generation)]
        if not self._warmup:
            cmd.append("--no-warmup")
        return cmd

    def _spawn(self, generation):
        with self._lock:
            slot = self._next_slot
            self._next_slot += 1
            self._desired[slot] = int(generation)
        self._sup.spawn(slot, generation)
        return slot

    # ---------------------------------------------------------- lifecycle --
    def start(self, wait_ready=True, timeout=None):
        """Spawn the initial generation, start the router + monitor;
        with ``wait_ready`` (default) block until every worker passed
        the health gate (or raise :class:`FleetError`)."""
        with self._lock:
            if self.state != "idle":
                return self
            self.state = "starting"
            self.generation = 1
            self._gen_dirs[1] = self.model_dir
        for _ in range(self._initial_workers):
            self._spawn(1)
        self._router = _RouterFront(self, self._host, self._port).start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="mxtpu-fleet-monitor")
        self._monitor.start()
        _flight.rec("fleet.start", self.name,
                    f"{self._initial_workers} worker(s) @ {self.url}")
        if wait_ready:
            self.wait_ready(timeout=timeout)
        with self._lock:
            if self.state == "starting":
                self.state = "serving"
        self._write_summary(force=True)
        return self

    @property
    def url(self):
        return self._router.url if self._router is not None else None

    def wait_ready(self, timeout=None, generation=None):
        """Block until every desired worker of `generation` (default:
        the active one) passes the health gate; FleetError on timeout."""
        deadline = time.monotonic() + (self.cfg["ready_timeout"]
                                       if timeout is None else timeout)
        while True:
            gen = self.generation if generation is None else generation
            want = [s for s, g in self._desired.items() if g == gen]
            ready = self._gated_ready(want)
            if want and len(ready) == len(want):
                # publish to the router NOW — the monitor's next pass
                # may be a poll period away and the caller is about to
                # send traffic
                self._refresh()
                return ready
            if time.monotonic() >= deadline:
                anns = _worker.read_workers(self.run_dir)
                states = {s: (anns.get(s) or {}).get("state", "absent")
                          for s in want}
                raise FleetError(
                    f"fleet workers not ready within the deadline: "
                    f"{states}; supervisor: "
                    f"{ {s: r['state'] for s, r in self._sup.census().items()} }")
            time.sleep(0.05)

    def stop(self, drain=True):
        """Retire every worker (graceful drain by default), stop the
        router + monitor, write the final summary."""
        with self._lock:
            if self.state in ("stopped", "idle"):
                self.state = "stopped"
                return
            self.state = "stopping"
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        self._sup.stop_all(graceful=drain)
        if self._router is not None:
            self._router.close()
        with self._lock:
            self.state = "stopped"
            self._routable = []  # _desired kept: the final fleet.json
            # census is the diagnose report's post-mortem view
        _flight.rec("fleet.stop", self.name)
        self._write_summary(force=True)

    # ------------------------------------------------------------ routing --
    def _gated_ready(self, slots):
        """Slots (of the given census) passing the announce health gate
        with a live, pid-matching process."""
        anns = _worker.read_workers(self.run_dir)
        census = self._sup.census()
        out = []
        for slot in slots:
            rec = census.get(slot)
            ann = anns.get(slot)
            if (rec and rec.get("alive") and gate_ready(ann)
                    and ann.get("pid") == rec.get("pid")
                    and ann.get("generation") == rec.get("generation")):
                out.append(slot)
                self._endpoints[slot] = (ann.get("host", "127.0.0.1"),
                                         int(ann["port"]))
        return out

    def _refresh(self):
        gen = self.generation
        want = sorted(s for s, g in self._desired.items() if g == gen)
        ready = self._gated_ready(want)
        now = time.monotonic()
        self._suspect = {s: t for s, t in self._suspect.items() if t > now}
        routable = [s for s in ready if s not in self._suspect]
        self._routable = routable or ready
        if self.cfg["policy"] == "hash":
            self._ring.rebuild(self._routable)

    def pick(self, model):
        """Policy-ordered candidate slots for one request."""
        self._rr += 1
        depths = None
        if self.cfg["policy"] == "least_loaded":
            depths = {s: m.get("queue_depth")
                      for s, m in self._last_sample.get(
                          "per_worker", {}).items()}
        return order_candidates(self.cfg["policy"], model,
                                self._routable, depths=depths,
                                rr=self._rr, ring=self._ring)

    def endpoint(self, slot):
        return self._endpoints.get(slot)

    def mark_suspect(self, slot, why=""):
        """A connection-level failure against `slot`: deprioritize it
        until the monitor re-verifies (or the supervisor respawns it)."""
        self._suspect[slot] = time.monotonic() + 1.0
        self._routable = [s for s in self._routable if s != slot]
        _flight.rec("fleet.suspect", f"slot{slot}", why)

    def _count(self, key, n=1):
        with self._count_lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def models(self):
        """The served model list (from any ready worker's announce)."""
        anns = _worker.read_workers(self.run_dir)
        for slot in self._routable:
            ann = anns.get(slot)
            if ann and ann.get("models"):
                return {"models": ann["models"],
                        "generation": ann.get("generation")}
        return {"models": [], "generation": self.generation}

    # ------------------------------------------------------------ scaling --
    def scale_to(self, n, reason="manual"):
        """Grow/shrink the active generation to `n` workers (scale-up
        spawns; scale-down drains the highest slots through exit 75)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"fleet cannot scale below 1 worker (got {n})")
        with self._lock:
            gen = self.generation
            active = sorted(s for s, g in self._desired.items()
                            if g == gen)
            if n > len(active):
                added = [self._spawn(gen) for _ in range(n - len(active))]
                _flight.rec("fleet.scale", "up",
                            f"{len(active)} -> {n} ({reason})")
                _logger.info("fleet: scale up %d -> %d (%s; slots %s)",
                             len(active), n, reason, added)
            elif n < len(active):
                dropped = active[n:]
                for slot in dropped:
                    self._desired.pop(slot, None)
                    self._sup.drain_slot(slot, reason=f"scale-down "
                                                      f"({reason})")
                _flight.rec("fleet.scale", "down",
                            f"{len(active)} -> {n} ({reason})")
                _logger.info("fleet: scale down %d -> %d (%s; drained "
                             "%s)", len(active), n, reason, dropped)
        self._write_summary(force=True)
        return n

    def _sample(self, now):
        gen = self.generation
        active = {s for s, g in self._desired.items() if g == gen}
        per = worker_metrics(self.run_dir, slots=active)
        per = {s: m for s, m in per.items()
               if m.get("generation") == gen}
        depths = [m["queue_depth"] for m in per.values()
                  if m.get("queue_depth") is not None]
        p99s = [m["p99_ms"] for m in per.values()
                if m.get("p99_ms") is not None]
        fills = [m["fill"] for m in per.values()
                 if m.get("fill") is not None]
        completed = sum(m.get("completed") or 0.0 for m in per.values())
        rps = None
        if self._last_completed is not None:
            t0, c0 = self._last_completed
            dt = now - t0
            if dt > 0:
                rps = max(0.0, (completed - c0) / dt)
        self._last_completed = (now, completed)
        sample = {"queue_depth": max(depths) if depths else None,
                  "p99_ms": max(p99s) if p99s else None,
                  "fill": max(fills) if fills else None,
                  "rps": rps, "completed": completed,
                  "per_worker": per}
        self._last_sample = sample
        return sample

    def _autoscale_tick(self, now):
        sample = self._sample(now)
        if self.cfg["max"] <= self.cfg["min"]:
            return  # fixed-size fleet: sampling still feeds the router
        if self.state != "serving":
            return
        with self._lock:
            active = sum(1 for g in self._desired.values()
                         if g == self.generation)
        direction, rec = self._scaler.decide(sample, active, now=now)
        if direction == "up":
            self.scale_to(min(self.cfg["max"], active + 1),
                          reason=f"autoscale: {rec['reason']}")
        elif direction == "down":
            self.scale_to(max(self.cfg["min"], active - 1),
                          reason=f"autoscale: {rec['reason']}")
        if direction:
            _flight.rec("fleet.autoscale", direction, rec["reason"])

    # ------------------------------------------------------------ rollout --
    def rollout(self, new_model_dir, timeout=None):
        """Zero-downtime model swap: spawn a generation-N+1 worker set
        from `new_model_dir` (warm from the shared disk compile cache),
        health-gate every new worker (announce census with zero pending
        compiles + live ``/healthz``), shift router traffic atomically,
        then drain generation N through exit 75. Returns the rollout
        record; raises :class:`FleetError` (old generation untouched)
        when the gate times out."""
        import urllib.request

        with self._lock:
            if self.state != "serving":
                raise FleetError(
                    f"rollout needs a serving fleet (state "
                    f"{self.state!r})")
            old_gen = self.generation
            new_gen = old_gen + 1
            self._gen_dirs[new_gen] = os.fspath(new_model_dir)
            old_slots = sorted(s for s, g in self._desired.items()
                               if g == old_gen)
            n = max(1, len(old_slots))
            # the autoscaler sits out the swap (state-gated): a census
            # change mid-rollout would race the generation accounting
            self.state = "rolling-out"
        rec = {"generation": new_gen,
               "model_dir": os.fspath(new_model_dir),
               "from_generation": old_gen, "t_start": time.time(),
               "workers": [], "drained": {}, "state": "spawning"}
        _flight.rec("fleet.rollout", f"gen{new_gen}",
                    os.fspath(new_model_dir))
        _logger.info("fleet: rollout -> generation %d (%s), %d worker(s)",
                     new_gen, new_model_dir, n)
        new_slots = [self._spawn(new_gen) for _ in range(n)]
        rec["workers"] = new_slots
        # ---- health gate: announce-ready + zero pending compiles + a
        # live /healthz answer from every new worker
        deadline = time.monotonic() + (self.cfg["ready_timeout"]
                                       if timeout is None else timeout)
        rec["state"] = "health-gate"
        while True:
            ready = self._gated_ready(new_slots)
            if len(ready) == len(new_slots):
                healthy = []
                for slot in ready:
                    host, port = self._endpoints[slot]
                    try:
                        with urllib.request.urlopen(
                                f"http://{host}:{port}/healthz",
                                timeout=2.0) as resp:
                            ok = json.loads(resp.read()).get(
                                "status") == "ok"
                    except (OSError, ValueError):
                        ok = False
                    if ok:
                        healthy.append(slot)
                if len(healthy) == len(new_slots):
                    break
            if time.monotonic() >= deadline:
                anns = _worker.read_workers(self.run_dir)
                states = {
                    s: {"state": (anns.get(s) or {}).get("state",
                                                         "absent"),
                        "pending_compiles":
                        (anns.get(s) or {}).get("pending_compiles")}
                    for s in new_slots}
                with self._lock:
                    for slot in new_slots:
                        self._desired.pop(slot, None)
                        self._sup.drain_slot(slot,
                                             reason="rollout aborted")
                rec["state"] = "aborted"
                rec["gate_failures"] = states
                self.rollouts.append(rec)
                with self._lock:
                    self.generation = old_gen
                    self._gen_dirs.pop(new_gen, None)
                    self.state = "serving"
                self._write_summary(force=True)
                raise FleetError(
                    f"rollout to generation {new_gen} aborted: health "
                    f"gate not passed within the deadline — {states} "
                    "(the old generation keeps serving)")
            time.sleep(0.05)
        # ---- atomic traffic shift, then drain the old generation
        with self._lock:
            self.generation = new_gen
        self._refresh()
        rec["state"] = "draining-old"
        rec["t_shift"] = time.time()
        _flight.rec("fleet.shift", f"gen{new_gen}",
                    f"{len(new_slots)} worker(s) live")
        with self._lock:
            for slot in old_slots:
                self._desired.pop(slot, None)
                self._sup.drain_slot(slot,
                                     reason=f"rollout gen{new_gen}")
        drain_deadline = time.monotonic() + self.cfg["drain_timeout"]
        while time.monotonic() < drain_deadline:
            self._sup.poll()
            left = [s for s in old_slots if s in self._sup.slots]
            if not left:
                break
            time.sleep(0.05)
        for ev in self._sup.events:
            if ev["kind"] in ("drained", "drain_killed") \
                    and ev["slot"] in old_slots:
                rec["drained"][str(ev["slot"])] = ev.get("exit_code")
        anns = _worker.read_workers(self.run_dir)
        rec["old_final"] = {
            str(s): {k: (anns.get(s) or {}).get(k)
                     for k in ("state", "admitted", "answered", "failed",
                               "drained")}
            for s in old_slots}
        rec["state"] = "done"
        rec["t_done"] = time.time()
        self.rollouts.append(rec)
        with self._lock:
            self.state = "serving"
        _logger.info("fleet: rollout to generation %d complete (old "
                     "generation exits: %s)", new_gen, rec["drained"])
        self._write_summary(force=True)
        return rec

    # ------------------------------------------------------------ monitor --
    def _monitor_loop(self):
        next_tick = 0.0
        while not self._stop_evt.is_set():
            try:
                self._sup.poll()
                self._refresh()
                now = time.monotonic()
                if now >= next_tick:
                    next_tick = now + self.cfg["interval"]
                    self._autoscale_tick(now)
                self._write_summary()
            except Exception:
                _logger.exception("fleet: monitor pass failed (fleet "
                                  "keeps serving)")
            self._stop_evt.wait(0.05)

    # -------------------------------------------------------------- state --
    def stats(self, light=False):
        """The fleet's aggregate observability snapshot (router /stats,
        fleet.json, diagnose)."""
        with self._lock:
            desired = dict(self._desired)
            gen = self.generation
        base = {"name": self.name, "state": self.state,
                "generation": gen, "policy": self.cfg["policy"],
                "desired": sum(1 for g in desired.values() if g == gen),
                "ready": len(self._routable)}
        if light:
            return base
        census = self._sup.census()
        anns = _worker.read_workers(self.run_dir)
        per = self._last_sample.get("per_worker", {})
        workers = {}
        for slot, g in sorted(desired.items()):
            rec = census.get(slot) or {}
            ann = anns.get(slot) or {}
            m = per.get(slot) or {}
            workers[str(slot)] = {
                "generation": g, "state": rec.get("state"),
                "alive": rec.get("alive"), "pid": rec.get("pid"),
                "restarts": rec.get("restarts"),
                "port": ann.get("port"), "ready": gate_ready(ann),
                "models": ann.get("models"),
                "queue_depth": m.get("queue_depth"),
                "p99_ms": m.get("p99_ms"), "rps": m.get("rps"),
                "shard_age_s": m.get("age_s"),
                "model_bus": ann.get("model_bus")}
        base.update({
            "url": self.url, "run_dir": self.run_dir,
            "bus_dir": self.bus_dir,
            "uptime_s": round(time.monotonic() - self._t_start, 1),
            "workers": workers,
            "router": dict(self._counters),
            "autoscaler": self._scaler.describe(),
            "sample": {k: self._last_sample.get(k) for k in
                       ("queue_depth", "p99_ms", "fill", "rps")},
            "rollouts": [
                {k: v for k, v in r.items() if k != "old_final"}
                for r in self.rollouts[-8:]],
            "supervisor": {"restarts_total": self._sup.restarts_total,
                           "drained_total": self._sup.drained_total},
        })
        return base

    def describe(self):
        """stats() + config + supervisor events (fleet.json)."""
        out = self.stats()
        out["config"] = dict(self.cfg)
        out["events"] = list(self._sup.events[-64:])
        return out

    def _write_summary(self, force=False):
        now = time.monotonic()
        if not force and now - self._summary_at < 1.0:
            return
        self._summary_at = now
        from .. import elastic as _elastic

        try:
            rec = self.describe()
            rec["updated"] = time.time()
            _elastic._atomic_json(
                os.path.join(self.run_dir, "fleet.json"), rec)
        except OSError as e:
            _logger.warning("fleet: could not write fleet.json: %s", e)


# --------------------------------------------------- telemetry collector ---

def _collect_serving_fleet():
    """Scrape-time gauges for the most recent live fleet in this
    process: rollout generation, census, fleet-wide completion rate and
    the router/autoscale counters (the per-worker gauge re-exports come
    from :mod:`mxnet_tpu.telemetry.fleet`'s shard collector)."""
    from ..telemetry import registry as _registry

    fleets = sorted(_LIVE, key=lambda f: f._t_start)
    if not fleets:
        return
    fl = fleets[-1]
    st = fl.stats(light=True)
    _registry.gauge("mxtpu_fleet_generation",
                    "Active fleet model generation (bumps per rollout)"
                    ).set(st["generation"])
    _registry.gauge("mxtpu_fleet_workers_desired",
                    "Workers the fleet wants in the active generation"
                    ).set(st["desired"])
    _registry.gauge("mxtpu_fleet_workers_ready",
                    "Workers currently routable").set(st["ready"])
    rps = fl._last_sample.get("rps")
    _registry.gauge("mxtpu_fleet_rps",
                    "Fleet-wide completion rate over the last "
                    "autoscaler interval").set(rps or 0.0)
    router = _registry.counter("mxtpu_fleet_router_requests_total",
                               "Router requests by outcome",
                               labels=("outcome",))
    with fl._count_lock:
        counters = dict(fl._counters)
    for outcome, n in counters.items():
        router.set_total(n, outcome)
    scale = _registry.counter("mxtpu_fleet_autoscale_total",
                              "Autoscaler actions", labels=("direction",))
    for direction, n in fl._scaler.decisions.items():
        scale.set_total(n, direction)
    _registry.counter("mxtpu_fleet_worker_restarts_total",
                      "Fleet worker slot restarts").set_total(
                          fl._sup.restarts_total)
    _registry.counter("mxtpu_fleet_workers_drained_total",
                      "Deliberately drained fleet workers (rollout / "
                      "scale-down / stop)").set_total(
                          fl._sup.drained_total)


def _install_collector():
    global _collector_installed
    if _collector_installed:
        return
    _collector_installed = True
    from ..telemetry import export as _export

    _export.register_collector("serving_fleet", _collect_serving_fleet)
