"""Per-model serving observability: latency percentiles, throughput,
queue depth, bucket census, batch fill ratio.

Counters are plain ints under one lock (the per-request cost is two lock
acquisitions — submit and complete); latencies go into a bounded ring so
a long-running server computes percentiles over recent traffic, not its
whole life. Everything flows into the existing profiler when a session is
recording (``serving[<model>]`` complete events + ``serving.<model>.*``
counter tracks via :func:`mxnet_tpu.profiler.record_serving`), and into
``tools/diagnose.py``'s "Serving" report via :meth:`snapshot`.
"""
from __future__ import annotations

import threading
import time
from collections import Counter, deque

from ..telemetry import flight as _flight

__all__ = ["ModelMetrics", "percentile"]

_RING = 8192  # recent-latency window for percentiles


def percentile(values, q):
    """Nearest-rank percentile of a sequence (no numpy dependency on the
    hot path; called only at snapshot time)."""
    if not values:
        return None
    xs = sorted(values)
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


class ModelMetrics:
    """Thread-safe serving counters for one served model."""

    def __init__(self, model):
        self.model = model
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0        # admission fast-rejects (busy + draining)
        self.failed = 0          # requests failed by a failed batch
        self.stalled = 0         # batches killed by a watchdog StallError
        self.batches = 0
        self.rows = 0            # real rows through compiled batches
        self.padded_rows = 0     # padding rows (bucket - rows per batch)
        self.bucket_census = Counter()
        self._lat_ms = deque(maxlen=_RING)
        self._t_first = None     # first completion (rps window start)
        self._t_last = None

    # ------------------------------------------------------- recording ---
    def record_submit(self):
        with self._lock:
            self.submitted += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1
        _flight.rec("serving.reject", self.model)
        from .. import profiler as _profiler

        if _profiler._RECORDING:
            _profiler.record_instant(f"serving.{self.model}.reject",
                                     cat="serving")

    def record_complete(self, lat_ms):
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self._lat_ms.append(lat_ms)
            if self._t_first is None:
                self._t_first = now
            self._t_last = now

    def record_fail(self, n=1):
        with self._lock:
            self.failed += n

    def record_batch(self, bucket, rows, dur_ms, queue_depth):
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.padded_rows += bucket - rows
            self.bucket_census[bucket] += 1
        _flight.rec("serving.batch", self.model,
                    f"bucket={bucket} rows={rows}")
        from .. import profiler as _profiler

        if _profiler._RECORDING:
            _profiler.record_serving(self.model, bucket, rows, dur_ms,
                                     queue_depth)

    def record_stall(self):
        with self._lock:
            self.stalled += 1
        _flight.rec("serving.stall", self.model)

    # -------------------------------------------------------- snapshot ---
    def snapshot(self, **extra):
        """One JSON-able dict: counters + p50/p95/p99 over the recent
        window + batch fill ratio + completion-window rps. ``extra``
        (live queue depth etc.) is merged in by the caller."""
        with self._lock:
            lat = list(self._lat_ms)
            padded = self.rows + self.padded_rows
            window = (self._t_last - self._t_first) \
                if (self._t_first is not None
                    and self._t_last is not None
                    and self._t_last > self._t_first) else None
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "stalled_batches": self.stalled,
                "batches": self.batches,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "batch_fill_ratio": round(self.rows / padded, 4)
                if padded else None,
                "bucket_census": dict(sorted(self.bucket_census.items())),
                "rps": round(self.completed / window, 2) if window else None,
            }
        for q, key in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
            v = percentile(lat, q)
            out[key] = round(v, 3) if v is not None else None
        out.update(extra)
        return out
