"""Per-model serving observability: latency percentiles, throughput,
queue depth, bucket census, batch fill ratio.

Counters are plain ints under one lock (the per-request cost is two lock
acquisitions — submit and complete); latencies go into a bounded ring so
a long-running server computes percentiles over recent traffic, not its
whole life. Everything flows into the existing profiler when a session is
recording (``serving[<model>]`` complete events + ``serving.<model>.*``
counter tracks via :func:`mxnet_tpu.profiler.record_serving`), and into
``tools/diagnose.py``'s "Serving" report via :meth:`snapshot`.
"""
from __future__ import annotations

import threading
import time
from collections import Counter, deque

from ..telemetry import flight as _flight

__all__ = ["ModelMetrics", "percentile"]

_RING = 8192  # recent-latency window for percentiles


def percentile(values, q):
    """Nearest-rank percentile of a sequence (no numpy dependency on the
    hot path; called only at snapshot time)."""
    if not values:
        return None
    xs = sorted(values)
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


class ModelMetrics:
    """Thread-safe serving counters for one served model."""

    def __init__(self, model):
        self.model = model
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0        # admission fast-rejects (busy + draining)
        self.failed = 0          # requests failed by a failed batch
        self.stalled = 0         # batches killed by a watchdog StallError
        self.batches = 0
        self.rows = 0            # real rows through compiled batches
        self.padded_rows = 0     # padding rows (bucket - rows per batch)
        self.bucket_census = Counter()
        self.deadline_dropped = Counter()   # {"submit": n, "queue": n}
        self.deadline_met = 0    # deadline-carrying requests answered in time
        self.deadline_missed = 0  # answered, but past their deadline
        self.cache_hits = 0
        self.cache_misses = 0
        self.coalesced = 0       # in-flight dupes folded onto a leader
        self._lat_ms = deque(maxlen=_RING)
        self._lat_by_class = {}  # priority -> deque ring
        self._t_first = None     # first completion (rps window start)
        self._t_last = None

    # ------------------------------------------------------- recording ---
    def record_submit(self):
        with self._lock:
            self.submitted += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1
        _flight.rec("serving.reject", self.model)
        from .. import profiler as _profiler

        if _profiler._RECORDING:
            _profiler.record_instant(f"serving.{self.model}.reject",
                                     cat="serving")

    def record_complete(self, lat_ms, priority=None):
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            self._lat_ms.append(lat_ms)
            if priority is not None:
                ring = self._lat_by_class.get(priority)
                if ring is None:
                    ring = self._lat_by_class[priority] = \
                        deque(maxlen=_RING // 4)
                ring.append(lat_ms)
            if self._t_first is None:
                self._t_first = now
            self._t_last = now

    def record_deadline_drop(self, where="queue"):
        """A deadline-doomed request dropped BEFORE a batch slot."""
        with self._lock:
            self.deadline_dropped[where] += 1
        _flight.rec("serving.deadline_drop", self.model, where)

    def record_deadline_outcome(self, met):
        with self._lock:
            if met:
                self.deadline_met += 1
            else:
                self.deadline_missed += 1

    def record_cache(self, hit):
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_coalesced(self):
        """A content-identical request attached to one already in flight
        (the idempotency half of hedging: a duplicate never double-runs
        a donating batch)."""
        with self._lock:
            self.coalesced += 1

    def record_fail(self, n=1):
        with self._lock:
            self.failed += n

    def record_batch(self, bucket, rows, dur_ms, queue_depth):
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.padded_rows += bucket - rows
            self.bucket_census[bucket] += 1
        _flight.rec("serving.batch", self.model,
                    f"bucket={bucket} rows={rows}")
        from .. import profiler as _profiler

        if _profiler._RECORDING:
            _profiler.record_serving(self.model, bucket, rows, dur_ms,
                                     queue_depth)

    def record_stall(self):
        with self._lock:
            self.stalled += 1
        _flight.rec("serving.stall", self.model)

    # -------------------------------------------------------- snapshot ---
    def snapshot(self, **extra):
        """One JSON-able dict: counters + p50/p95/p99 over the recent
        window + batch fill ratio + completion-window rps. ``extra``
        (live queue depth etc.) is merged in by the caller."""
        with self._lock:
            lat = list(self._lat_ms)
            by_class = {p: list(r) for p, r in self._lat_by_class.items()}
            padded = self.rows + self.padded_rows
            window = (self._t_last - self._t_first) \
                if (self._t_first is not None
                    and self._t_last is not None
                    and self._t_last > self._t_first) else None
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "stalled_batches": self.stalled,
                "batches": self.batches,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "batch_fill_ratio": round(self.rows / padded, 4)
                if padded else None,
                "bucket_census": dict(sorted(self.bucket_census.items())),
                "rps": round(self.completed / window, 2) if window else None,
                "deadline_dropped": dict(self.deadline_dropped),
                "deadline_met": self.deadline_met,
                "deadline_missed": self.deadline_missed,
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "coalesced": self.coalesced,
            }
            cache_total = self.cache_hits + self.cache_misses
            out["cache_hit_ratio"] = (round(self.cache_hits / cache_total, 4)
                                      if cache_total else None)
        for q, key in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
            v = percentile(lat, q)
            out[key] = round(v, 3) if v is not None else None
        if by_class:
            out["by_class"] = {
                p: {"count": len(r),
                    "p50_ms": round(percentile(r, 50), 3) if r else None,
                    "p99_ms": round(percentile(r, 99), 3) if r else None}
                for p, r in sorted(by_class.items())}
        out.update(extra)
        return out
