"""Automatic naming scopes (parity: `python/mxnet/name.py`).

`NameManager` generates unique names for anonymously-created symbols;
`Prefix` prepends a fixed prefix to every auto-generated name:

    with mx.name.Prefix("mlp_"):
        net = mx.sym.FullyConnected(data, num_hidden=10)
    # net.name == "mlp_fullyconnected0"

Scopes are thread-local and nest; the innermost manager wins. Each
manager owns its counters, so entering a fresh ``NameManager()``
restarts numbering — exporting the same network twice under fresh
scopes yields identical node names (the reference contract).
"""
from __future__ import annotations

import threading

from .base import name_manager as _default_counters

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Auto-name generator (parity: name.py NameManager). `get(name,
    hint)` returns `name` unchanged when the user supplied one, else a
    unique `hint`-based name from this manager's own counters."""

    _tls = threading.local()

    def __init__(self):
        self._counters = {}

    def get(self, name, hint):
        if name:
            return name
        idx = self._counters.get(hint, 0)
        self._counters[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        stack = getattr(NameManager._tls, "stack", None)
        if stack is None:
            stack = NameManager._tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        NameManager._tls.stack.pop()


class Prefix(NameManager):
    """Prefixing name manager (parity: name.py Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(None, hint)


class _DefaultNameManager(NameManager):
    """The ambient manager outside any scope: backed by the process-wide
    (thread-local) counter table in `base`, so default auto-names stay
    globally unique across the nd/sym/gluon entry points."""

    def get(self, name, hint):
        return name if name else _default_counters.get(hint)


_DEFAULT = _DefaultNameManager()


def current():
    """The innermost active manager (the default one outside any scope)."""
    stack = getattr(NameManager._tls, "stack", None)
    return stack[-1] if stack else _DEFAULT
