"""Stateful random number generation over JAX's functional PRNG.

Parity target: `RandGenerator<cpu/gpu>` in the reference
(`include/mxnet/random_generator.h:42-141`): per-device stateful generators
(1024 mt19937 / curand Philox states) seeded by `mx.random.seed`.

TPU-native: JAX PRNG is functional (threefry keys). This module owns the
*stateful* wrapper: a global seed + a split counter. Every imperative random
op draws `next_key()`; hybridized graphs receive a key as an extra traced
input so the compiled executable stays pure. `seed()` resets the stream
(optionally per-context, matching `mx.random.seed(..., ctx=...)`).
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed"]

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        import jax

        _state.seed = 0
        _state.key = jax.random.PRNGKey(0)


def seed(seed_state: int, ctx=None) -> None:
    """Seed the global generator (parity: mx.random.seed)."""
    import jax

    _state.seed = int(seed_state)
    _state.key = jax.random.PRNGKey(int(seed_state))


def current_seed() -> int:
    _ensure()
    return _state.seed


def next_key():
    """Draw a fresh PRNG key, advancing the global stream.

    Inside a CachedOp trace, keys come from the scope's traced key input so
    compiled graphs stay pure yet advance with the global stream per call."""
    import jax

    from . import cached_op

    scope = cached_op.current_trace()
    if scope is not None:
        return scope.next_key()
    _ensure()
    _state.key, sub = jax.random.split(_state.key)
    return sub
