"""Network visualization (parity: `python/mxnet/visualization.py`).

`print_summary` renders the Keras-style per-layer table (layer name/type,
output shape, param count, previous layers, plus totals); `plot_network`
emits a graphviz Digraph when the `graphviz` package is installed (it is
not part of the baked environment, so it is import-gated exactly like the
reference, which raises ImportError with guidance).
"""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a per-layer summary table (parity: visualization.py:34).

    Parameters
    ----------
    symbol : Symbol
    shape : dict of str -> tuple, optional
        Input shapes (by variable name) used to infer per-layer output
        shapes and parameter counts.
    """
    from .symbol.symbol import _topo

    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, pos):
        line = ""
        for field, p in zip(fields, pos):
            line += str(field)
            line = line[:p - 1] + " " * max(1, p - len(line))
        print(line)

    print("_" * line_length)
    print_row(headers, positions)
    print("=" * line_length)

    order = _topo(symbol._entries)
    input_names = set(symbol.list_arguments()) | \
        set(symbol.list_auxiliary_states())
    total_params = 0
    for node in order:
        if node.is_var:
            continue
        name = node.name
        out_name = name + "_output" if node.num_outputs == 1 \
            else name + "_output0"
        out_shape = shape_dict.get(out_name, "")
        # params: variable inputs that belong to this layer (prefix match)
        cur_params = 0
        pre_layers = []
        for child, _ in node.inputs:
            if child.is_var:
                # declared inputs (user shape dict) and label vars are
                # DATA, not parameters, even when they prefix-match the
                # layer name (auto-created '<name>_label' does)
                is_data = child.name in (shape or {}) or \
                    child.name.endswith("_label")
                if not is_data and child.name.startswith(name):
                    # the layer's own parameters: counted, never listed
                    # as previous layers
                    if shape_dict.get(child.name):
                        n = 1
                        for d in shape_dict[child.name]:
                            n *= d
                        cur_params += n
                elif child.name in input_names:
                    pre_layers.append(child.name)
            else:
                pre_layers.append(child.name)
        total_params += cur_params
        fields = [f"{name}({node.op})",
                  str(tuple(out_shape)) if out_shape != "" else "",
                  cur_params, ",".join(pre_layers[:3])]
        print_row(fields, positions)
        print("_" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the network (parity:
    visualization.py:214). Requires the optional `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("Draw network requires graphviz library")
    from .symbol.symbol import _topo

    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs or {})
    dot = Digraph(name=title, format=save_format)
    order = _topo(symbol._entries)
    # palette per op family (reference's color scheme)
    palette = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
               "BatchNorm": "#bebada", "Activation": "#ffffb3",
               "Pooling": "#80b1d3", "Concat": "#fdb462",
               "softmax": "#fccde5"}
    names = set()
    for node in order:
        if node.is_var and hide_weights and \
                node.name not in symbol.list_arguments()[:1]:
            # weights/aux hidden; data-like vars kept
            if node.attrs.get("__is_aux__") or any(
                    node.name.endswith(s)
                    for s in ("weight", "bias", "gamma", "beta",
                              "moving_mean", "moving_var")):
                continue
        color = palette.get(node.op or "", "#8dd3c7")
        label = node.name if node.is_var else f"{node.op}\n{node.name}"
        dot.node(node.name, label=label, fillcolor=color, **node_attr)
        names.add(node.name)
    for node in order:
        if node.name not in names:
            continue
        for child, _ in node.inputs:
            if child.name in names:
                dot.edge(child.name, node.name)
    return dot
