"""Checkpoint helpers for the symbolic path.

Parity target: `python/mxnet/model.py:403-476` — `save_checkpoint` emits
`prefix-symbol.json` + `prefix-%04d.params`, `load_checkpoint` reads them
back. The `.params` payload goes through `mx.nd.save/load`, keyed with the
reference's `arg:`/`aux:` prefixes so Gluon `SymbolBlock.imports` and
Module.load share one on-disk contract.
"""
from __future__ import annotations

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

from .module.base_module import BatchEndParam  # noqa: F401  (parity re-export)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """parity: model.py:403. Both files are written atomically
    (tmp + fsync + os.replace, mxnet_tpu.checkpoint) — a run killed
    mid-save leaves the previous checkpoint intact, never a torn file."""
    from .checkpoint import atomic_write
    from .ndarray import utils as nd_utils

    if symbol is not None:
        atomic_write(f"{prefix}-symbol.json", symbol.save)
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    atomic_write(f"{prefix}-{epoch:04d}.params",
                 lambda tmp: nd_utils.save(tmp, save_dict))


def load_params(fname):
    """Split a params file into (arg_params, aux_params) dicts.

    Missing files raise FileNotFoundError naming the path; undeserializable
    files raise a clear "corrupt params file" ValueError instead of a raw
    zipfile/numpy error (robustness parity: the reference's load paths
    surface the offending path)."""
    import os

    from .ndarray import utils as nd_utils

    if not os.path.exists(fname):
        raise FileNotFoundError(f"params file not found: {fname!r}")
    try:
        loaded = nd_utils.load(fname)
    except Exception as e:
        raise ValueError(
            f"corrupt params file {fname!r}: {type(e).__name__}: {e} "
            "(truncated write or not an mx.nd.save container — if this "
            "came from a CheckpointManager directory, load through the "
            "manager to fall back to the previous good checkpoint)") from e
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """parity: model.py:448 — returns (symbol, arg_params, aux_params).
    Raises FileNotFoundError / "corrupt" ValueError naming the offending
    file rather than surfacing raw deserialization errors."""
    import os

    from . import symbol as sym_mod

    sym_file = f"{prefix}-symbol.json"
    if not os.path.exists(sym_file):
        raise FileNotFoundError(
            f"symbol file not found: {sym_file!r} (checkpoint prefix "
            f"{prefix!r}, epoch {epoch})")
    try:
        symbol = sym_mod.load(sym_file)
    except Exception as e:
        raise ValueError(
            f"corrupt symbol file {sym_file!r}: "
            f"{type(e).__name__}: {e}") from e
    arg_params, aux_params = load_params(f"{prefix}-{epoch:04d}.params")
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy model API (parity: python/mxnet/model.py:555 FeedForward —
    deprecated in the reference in favor of Module, kept for the scripts
    that still use it). Thin adapter over :class:`mxnet_tpu.module.Module`.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # ------------------------------------------------------------- train ---
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        import logging as _logging

        from .module import Module

        data = self._as_iter(X, y)
        mod = Module(self.symbol, context=self.ctx,
                     logger=logger or _logging)
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=self.kwargs,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=self.allow_extra_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1, monitor=monitor)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        import numpy as _np

        mod = self._require_module(X)
        out = mod.predict(self._as_iter(X), num_batch=num_batch)
        return out.asnumpy() if hasattr(out, "asnumpy") else _np.asarray(out)

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        mod = self._require_module(X)
        res = mod.score(self._as_iter(X), eval_metric, num_batch=num_batch)
        return res[0][1] if isinstance(res, list) else res

    # ------------------------------------------------------ persistence ---
    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        save_checkpoint(prefix, epoch or 0, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """parity: model.py FeedForward.create — construct + fit."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        return model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, logger=logger)

    # ---------------------------------------------------------- helpers ---
    def _as_iter(self, X, y=None):
        from .io import NDArrayIter, DataIter

        if isinstance(X, DataIter):
            if hasattr(X, "reset"):
                X.reset()
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size)

    def _require_module(self, X):
        if self._module is not None:
            return self._module
        from .module import Module

        data = self._as_iter(X)
        label_shapes = list(getattr(data, "provide_label", []) or [])
        if not label_shapes:
            # label-less prediction: the loss heads still declare label
            # inputs (SoftmaxOutput), unused at inference — feed shapes
            # (reference FeedForward.predict likewise tolerates no labels)
            batch = data.provide_data[0][1][0]
            label_shapes = [(n, (batch,))
                            for n in self.symbol.list_arguments()
                            if n.endswith("_label")]
        mod = Module(self.symbol, context=self.ctx)
        mod.bind(data_shapes=data.provide_data,
                 label_shapes=label_shapes or None, for_training=False)
        mod.set_params(self.arg_params or {}, self.aux_params or {},
                       allow_missing=False)
        self._module = mod
        return mod


__all__ += ["FeedForward", "load_params"]
