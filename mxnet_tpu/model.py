"""Checkpoint helpers for the symbolic path.

Parity target: `python/mxnet/model.py:403-476` — `save_checkpoint` emits
`prefix-symbol.json` + `prefix-%04d.params`, `load_checkpoint` reads them
back. The `.params` payload goes through `mx.nd.save/load`, keyed with the
reference's `arg:`/`aux:` prefixes so Gluon `SymbolBlock.imports` and
Module.load share one on-disk contract.
"""
from __future__ import annotations

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

from .module.base_module import BatchEndParam  # noqa: F401  (parity re-export)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """parity: model.py:403."""
    from .ndarray import utils as nd_utils

    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd_utils.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(fname):
    """Split a params file into (arg_params, aux_params) dicts."""
    from .ndarray import utils as nd_utils

    loaded = nd_utils.load(fname)
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """parity: model.py:448 — returns (symbol, arg_params, aux_params)."""
    from . import symbol as sym_mod

    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(f"{prefix}-{epoch:04d}.params")
    return symbol, arg_params, aux_params
