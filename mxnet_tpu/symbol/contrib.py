"""`mx.sym.contrib` — symbolic contrib op namespace (parity:
`python/mxnet/symbol/contrib.py`). The `_contrib_*` registry ops exposed
unprefixed for graph building; symbolic control flow is served by the
hybridize path (Python `mx.nd.contrib.foreach`/`while_loop`/`cond`
callables trace into `lax.scan`/`cond` inside the compiled executable, so
no separate subgraph-op representation is needed)."""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry
from . import _make_wrapper

_mod = _sys.modules[__name__]
for _name in _registry.list_ops():
    _op = _registry.get(_name)
    for _cand in (_name,) + _op.aliases:
        if _cand.startswith("_contrib_"):
            _short = _cand[len("_contrib_"):]
            if not hasattr(_mod, _short):
                setattr(_mod, _short, _make_wrapper(_name))
