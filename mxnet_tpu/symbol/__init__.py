"""mx.sym — symbolic API surface.

Parity target: `python/mxnet/symbol/` — every registered op is exposed as
a composition function (the reference generates these from the op registry
at install time; here they are built at import from `ops/registry.py`).
"""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry
from .symbol import (Symbol, var, Variable, Group, load, load_json,
                     zeros, ones, arange)
from .symbol import _apply_op

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange", "invoke"]


def invoke(op_name, *inputs, **kwargs):
    """Symbolic analogue of `mx.nd.invoke` — the F-protocol entry point
    used by HybridBlock tracing (F.invoke(...))."""
    return _apply_op(op_name, [i for i in inputs if i is not None], kwargs)


def _make_wrapper(op_name):
    def wrapper(*args, **kwargs):
        return _apply_op(op_name, list(args), kwargs)

    wrapper.__name__ = op_name
    wrapper.__qualname__ = op_name
    wrapper.__doc__ = (_registry.get(op_name).fn.__doc__ or
                       f"symbolic wrapper for op {op_name!r}")
    return wrapper


_mod = _sys.modules[__name__]
for _name in _registry.list_ops():
    _op = _registry.get(_name)
    for _exposed in (_name,) + _op.aliases:
        if not hasattr(_mod, _exposed):
            setattr(_mod, _exposed, _make_wrapper(_name))

# contrib namespace (imported last: needs _make_wrapper + full registry)
from . import contrib  # noqa: E402,F401
