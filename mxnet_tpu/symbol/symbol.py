"""Symbol: the lazy op-graph half of the two programming models.

Parity target: `python/mxnet/symbol/symbol.py` (compose :~500, infer_shape,
`simple_bind` :1666, json save :1334, `load`) over nnvm Symbol/Graph
(`3rdparty/tvm/nnvm`). The reference builds an nnvm DAG of op nodes whose
attributes (FInferShape/FGradient/FCompute) drive GraphExecutor.

TPU-native redesign: a Symbol is a pure-Python DAG over the same op
registry the imperative path uses (`ops/registry.py`). "bind" does not
build executors node-by-node — the whole graph lowers to ONE pure JAX
function (topological walk applying each op's jax fn) which XLA compiles
into a single fused executable per (shape, train-mode) signature. Memory
planning, op fusion and bulking (`src/nnvm/plan_memory.cc:330`,
`GraphExecutor::InitOpSegs`) are all subsumed by XLA compilation.

Training-dependent behaviour (BatchNorm stats, Dropout) is NOT baked into
the graph: the eval function takes a `training` flag and an rng key, and
ops whose signature declares `training` / `key` get them injected at that
point — the analogue of the reference's `is_train` executor flag and
kRandom resource.

Auxiliary states (BatchNorm moving stats) follow the reference contract:
they are graph inputs that are functionally updated during a training
forward; the new values are returned as extra outputs and written back by
the Executor (`attach aux-state writeback`, `graph_executor.cc`).
"""
from __future__ import annotations

import ast
import inspect
import json
from collections import OrderedDict

import numpy as _np

from .. import _amp_core
from ..base import MXNetError, canonical_dtype
from ..ops import registry as _registry

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange"]


# --------------------------------------------------------------------------
# auto-created parameter inputs per layer op: (arg_name, is_aux, skip_if)
# skip_if is a predicate over the static attrs.
_LAYER_PARAMS = {
    "FullyConnected": [("weight", False, None),
                       ("bias", False, lambda a: a.get("no_bias", False))],
    "Convolution": [("weight", False, None),
                    ("bias", False, lambda a: a.get("no_bias", False))],
    "Deconvolution": [("weight", False, None),
                      ("bias", False, lambda a: a.get("no_bias", True))],
    "BatchNorm": [("gamma", False, None), ("beta", False, None),
                  ("moving_mean", True, None), ("moving_var", True, None)],
    "LayerNorm": [("gamma", False, None), ("beta", False, None)],
    "GroupNorm": [("gamma", False, None), ("beta", False, None)],
    "InstanceNorm": [("gamma", False, None), ("beta", False, None)],
    "Embedding": [("weight", False, None)],
    "RNN": [("params", False, None)],
    "LeakyReLU": [("gamma", False,
                   lambda a: a.get("act_type", "leaky") != "prelu")],
    # loss heads auto-create their label input as '<name>_label' when not
    # supplied (reference: mx.sym.SoftmaxOutput(net, name='softmax') then
    # list_arguments() contains 'softmax_label')
    "SoftmaxOutput": [("label", False, None)],
    "SVMOutput": [("label", False, None)],
    "LinearRegressionOutput": [("label", False, None)],
    "LogisticRegressionOutput": [("label", False, None)],
    "MAERegressionOutput": [("label", False, None)],
}

# canonical classification sets live with the op schema layer so graph
# composition and schema dumps cannot drift apart
from ..ops.schema import RUNTIME_PARAMS as _RUNTIME_PARAMS  # noqa: E402


def _op_kwargs(node):
    """Node attrs minus dunder-keyed user/scope attributes (AttrScope,
    __shape__/__lr_mult__-style) — only real operator parameters may
    reach the op callable."""
    from ..attribute import is_dunder

    return {k: v for k, v in node.attrs.items() if not is_dunder(k)}


def _sig_params(op):
    try:
        return list(inspect.signature(op.fn).parameters.values())
    except (TypeError, ValueError):
        return []


class _Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "_id")

    def __init__(self, op, name, attrs=None, inputs=(), num_outputs=1):
        self.op = op                  # registry op name, or None = variable
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)    # [(node, out_idx), ...]
        self.num_outputs = num_outputs

    @property
    def is_var(self):
        return self.op is None

    @property
    def is_aux(self):
        return self.is_var and self.attrs.get("__is_aux__", False)


def _topo(entries):
    """Post-order unique node list for the subgraph feeding `entries`."""
    seen = set()
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child, _ in node.inputs:
            visit(child)
        order.append(node)

    for node, _ in entries:
        visit(node)
    return order


class Symbol:
    """An output list over the graph (parity: symbol.py Symbol).

    `_entries` is a list of (node, out_index); most symbols have one.
    """

    def __init__(self, entries):
        self._entries = list(entries)

    # ------------------------------------------------------------ basics --
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __iter__(self):
        return (Symbol([e]) for e in self._entries)

    def __len__(self):
        return len(self._entries)

    def __getitem__(self, index):
        if isinstance(index, str):
            for e in self._entries:
                if _output_name(e) == index or e[0].name == index:
                    return Symbol([e])
            raise ValueError(f"no output named {index!r}; outputs: "
                             f"{self.list_outputs()}")
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    def __repr__(self):
        return f"<Symbol {self.name or 'group'}>"

    def __copy__(self):
        return Symbol(self._entries)

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # ------------------------------------------------------- graph lists --
    def list_arguments(self):
        return [n.name for n in _topo(self._entries)
                if n.is_var and not n.is_aux]

    def list_auxiliary_states(self):
        return [n.name for n in _topo(self._entries) if n.is_aux]

    def list_inputs(self):
        return [n.name for n in _topo(self._entries) if n.is_var]

    def list_outputs(self):
        return [_output_name(e) for e in self._entries]

    def get_internals(self):
        """Every node output as a group (parity: symbol.py get_internals)."""
        entries = []
        for node in _topo(self._entries):
            for i in range(node.num_outputs):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        nodes = {id(n): n for n, _ in self._entries}
        child_entries = []
        for n in nodes.values():
            child_entries.extend(n.inputs)
        return Symbol(child_entries) if child_entries else None

    # -------------------------------------------------------------- attrs --
    def attr(self, key):
        from ..attribute import dunder, is_dunder

        if len(self._entries) == 1:
            attrs = self._entries[0][0].attrs
            value = attrs.get(key)
            if value is None and not is_dunder(key):
                # AttrScope attrs are stored dunder-normalized
                value = attrs.get(dunder(key))
            return None if value is None else str(value)
        return None

    def list_attr(self):
        if len(self._entries) == 1:
            return {k: str(v) for k, v in self._entries[0][0].attrs.items()}
        return {}

    def attr_dict(self):
        out = {}
        for node in _topo(self._entries):
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for e in self._entries:
            e[0].attrs.update(kwargs)

    # ------------------------------------------------------------ verify --
    def verify(self, type_dict=None, raise_on_error=True, **shape_hints):
        """Run the static graph verifier (parity role: NNVM's pre-execution
        InferShape/InferType passes + dmlc parameter validation).

        Checks, without executing any device code: per-node kwargs against
        the op schemas, shape/dtype inference consistency, dangling or
        duplicate-name inputs, cycles, dead outputs, and unused hints.
        Returns the full :class:`~mxnet_tpu.analysis.verify.Issue` list
        (warnings included); raises
        :class:`~mxnet_tpu.analysis.verify.GraphVerifyError` when
        error-severity issues exist and ``raise_on_error`` is set.

        ``shape_hints``/``type_dict`` mirror ``infer_shape``/``infer_type``
        keywords and deepen the checked surface — without hints only
        structural and kwarg passes can fire.
        """
        from ..analysis.verify import raise_if_errors, verify_graph

        issues = verify_graph(self, shape_hints, type_dict)
        if raise_on_error:
            raise_if_errors(issues)
        return issues

    # -------------------------------------------------------- shape/type --
    def infer_shape(self, **kwargs):
        """Forward shape inference (parity: symbol.py infer_shape).

        Known input shapes propagate through the graph; layer-op parameter
        shapes (weights/biases/stats) are derived from their data input via
        per-op rules — the practical core of the reference's bidirectional
        FInferShape fixed point.
        Returns (arg_shapes, out_shapes, aux_shapes) in
        list_arguments()/list_outputs()/list_auxiliary_states() order.
        """
        try:
            shapes, _ = self._infer(kwargs, {})
        except MXNetError:
            raise
        except Exception as exc:  # noqa: BLE001 - inference failure surface
            raise MXNetError(f"infer_shape failed: {exc}") from exc
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes["var", n] for n in self.list_arguments()]
        aux_shapes = [shapes["var", n] for n in self.list_auxiliary_states()]
        out_shapes = [shapes[e] for e in
                      ((id(n), i) for n, i in self._entries)]
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, **kwargs):
        try:
            return self.infer_shape(**kwargs)
        except MXNetError:
            return None, None, None

    def infer_type(self, **kwargs):
        dtypes = {k: canonical_dtype(v) for k, v in kwargs.items()}
        try:
            _, types = self._infer({}, dtypes)
            arg_t = [types["var", n] for n in self.list_arguments()]
            aux_t = [types["var", n] for n in self.list_auxiliary_states()]
            out_t = [types[(id(n), i)] for n, i in self._entries]
            return arg_t, out_t, aux_t
        except Exception:  # noqa: BLE001 — fall back to shape-free inference
            return self._infer_type_only(dtypes)

    def _infer_type_only(self, dtype_hints):
        """Shape-free dtype propagation (the reference's FInferType works
        without shapes; here: numpy promotion + explicit dtype attrs)."""
        import numpy as np

        types = {}
        for node in _topo(self._entries):
            try:
                if node.is_var:
                    types[id(node), 0] = np.dtype(canonical_dtype(
                        dtype_hints.get(
                            node.name,
                            node.attrs.get("__dtype__", "float32"))))
                    continue
                if "dtype" in node.attrs and node.attrs["dtype"] is not None:
                    dt = np.dtype(canonical_dtype(node.attrs["dtype"]))
                else:
                    in_ts = [types[id(c), oi] for c, oi in node.inputs]
                    dt = (np.result_type(*in_ts) if in_ts
                          else np.dtype("float32"))
            except MXNetError:
                raise
            except Exception as exc:  # noqa: BLE001 — add node diagnostics
                raise MXNetError(
                    f"infer_type: node {node.name!r}"
                    f"{f' (op {node.op})' if node.op else ''}: "
                    f"{exc}") from exc
            for i in range(node.num_outputs):
                types[id(node), i] = dt
        arg_t = [types[id(n), 0] for n in _topo(self._entries)
                 if n.is_var and not n.is_aux]
        aux_t = [types[id(n), 0] for n in _topo(self._entries) if n.is_aux]
        out_t = [types[id(n), i] for n, i in self._entries]
        return arg_t, out_t, aux_t

    def _infer(self, shape_hints, dtype_hints):
        """Shared shape+dtype inference walk. Returns (shapes, dtypes) maps
        keyed by ("var", name) for inputs and (node_id, out_idx) for
        intermediate outputs."""
        import jax

        shapes = {}
        dtypes = {}
        vals = {}  # (node_id, out_idx) -> ShapeDtypeStruct

        def var_struct(node):
            shape = shape_hints.get(node.name, node.attrs.get("__shape__"))
            dtype = dtype_hints.get(node.name,
                                    node.attrs.get("__dtype__", "float32"))
            if shape is None:
                return None
            return jax.ShapeDtypeStruct(tuple(shape), canonical_dtype(dtype))

        for node in _topo(self._entries):
            if node.is_var:
                st = var_struct(node)
                if st is not None:
                    vals[id(node), 0] = st
                    shapes["var", node.name] = tuple(st.shape)
                    shapes[id(node), 0] = tuple(st.shape)
                    dtypes["var", node.name] = st.dtype
                    dtypes[id(node), 0] = st.dtype
                continue
            in_structs = []
            data_struct = None
            for child, oi in node.inputs:
                st = vals.get((id(child), oi))
                if st is not None and data_struct is None:
                    data_struct = st
                in_structs.append((child, oi, st))
            # resolve unknown parameter-var inputs from the data input
            rules = _param_shape_rules(node, data_struct)
            resolved = []
            for child, oi, st in in_structs:
                if st is None:
                    if child.is_var and child.name in rules:
                        rshape, rdtype = rules[child.name]
                        st = jax.ShapeDtypeStruct(
                            rshape,
                            canonical_dtype(
                                dtype_hints.get(
                                    child.name,
                                    child.attrs.get(
                                        "__dtype__",
                                        rdtype or "float32"))))
                        vals[id(child), 0] = st
                        shapes["var", child.name] = tuple(st.shape)
                        shapes[id(child), 0] = tuple(st.shape)
                        dtypes["var", child.name] = st.dtype
                        dtypes[id(child), 0] = st.dtype
                    else:
                        raise MXNetError(
                            f"cannot infer shape of input {child.name!r} "
                            f"to op {node.name!r} ({node.op})")
                resolved.append(st)
            try:
                outs = _eval_shape_node(node, resolved)
            except Exception as exc:  # noqa: BLE001 — add node diagnostics
                from ..analysis.verify import node_failure_message

                raise MXNetError(node_failure_message(
                    node, [tuple(st.shape) for st in resolved],
                    exc)) from exc
            for i, st in enumerate(outs):
                vals[id(node), i] = st
                shapes[id(node), i] = tuple(st.shape)
                dtypes[id(node), i] = st.dtype
        return shapes, dtypes

    # --------------------------------------------------------------- eval --
    def _build_eval(self):
        """The whole graph as one pure function:
        fn(arg_vals: dict, aux_vals: dict, rng_key, training)
          -> (out_raws: list, new_aux: dict)
        """
        order = _topo(self._entries)
        entries = [(id(n), i) for n, i in self._entries]

        def run(arg_vals, aux_vals, rng_key, training):
            import jax

            vals = {}
            new_aux = {}
            for node in order:
                if node.is_var:
                    if node.is_aux:
                        vals[id(node), 0] = aux_vals[node.name]
                    else:
                        vals[id(node), 0] = arg_vals[node.name]
                    continue
                op = _registry.get(node.op)
                in_raws = [vals[id(c), oi] for c, oi in node.inputs]
                if _amp_core.ACTIVE:
                    in_raws = _amp_core.cast_inputs(node.op, in_raws)
                kwargs = _op_kwargs(node)
                sig_names = [p.name for p in _sig_params(op)]
                is_train = training and not kwargs.get("use_global_stats",
                                                       False)
                if "training" in sig_names:
                    kwargs["training"] = is_train
                if "key" in sig_names and "key" not in kwargs:
                    # random/dropout ops draw from the threaded key stream
                    # (reference: Resource kRandom attached per node)
                    rng_key, sub = jax.random.split(rng_key)
                    kwargs["key"] = sub
                out = op.fn(*in_raws, **kwargs)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                for i, o in enumerate(outs):
                    vals[id(node), i] = o
                if node.op == "BatchNorm" and is_train:
                    _bn_aux_update(node, outs, aux_vals, new_aux)
            return [vals[e] for e in entries], new_aux

        return run

    def eval_nd(self, feed, aux_handles=None):
        """Evaluate with NDArrays THROUGH the imperative op path, so the
        autograd tape records every node and parameter NDArrays receive
        gradients (the reference's SymbolBlock runs through the same
        CachedOp/imperative machinery as any Gluon block).

        feed maps input names (args AND aux) to NDArrays; training-mode aux
        updates (BatchNorm moving stats) are written back into the handles
        in `aux_handles` (or `feed`) via `cached_op.update_state`, which is
        trace-safe under hybridize.
        """
        from .. import autograd
        from .. import ndarray as nd_mod
        from ..cached_op import update_state

        aux_handles = aux_handles or {}
        vals = {}
        training = autograd.is_training()
        for node in _topo(self._entries):
            if node.is_var:
                try:
                    vals[id(node), 0] = feed[node.name]
                except KeyError:
                    raise MXNetError(
                        f"eval is missing input {node.name!r}") from None
                continue
            op = _registry.get(node.op)
            in_nds = [vals[id(c), oi] for c, oi in node.inputs]
            kwargs = _op_kwargs(node)
            sig_names = [p.name for p in _sig_params(op)]
            is_train = training and not kwargs.get("use_global_stats", False)
            if "training" in sig_names and node.op != "Dropout":
                kwargs["training"] = is_train
            kwargs.pop("key", None)  # rng handled by the nd wrappers
            out = getattr(nd_mod, node.op)(*in_nds, **kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for i, o in enumerate(outs):
                vals[id(node), i] = o
            if node.op == "BatchNorm" and is_train:
                momentum = kwargs.get("momentum", 0.9)
                for stat_idx, inp_idx in ((1, 3), (2, 4)):
                    child, _ = node.inputs[inp_idx]
                    handle = aux_handles.get(child.name, feed.get(child.name))
                    if handle is None or not child.is_aux:
                        continue
                    with autograd.pause():
                        batch = outs[stat_idx].astype(handle.dtype)
                        update_state(handle, handle * momentum
                                     + batch * (1 - momentum))
        wrapped = [vals[id(n), i] for n, i in self._entries]
        return wrapped[0] if len(wrapped) == 1 else wrapped

    def eval_with(self, feed, param_feed=None, training=False):
        """Evaluate with NDArray feeds; returns NDArray or list of them.
        (Used by SymbolBlock / Symbol.eval.)"""
        from .. import random as _random
        from ..ndarray import NDArray

        all_feed = dict(feed)
        if param_feed:
            all_feed.update(param_feed)
        raw = {k: (v._data if isinstance(v, NDArray) else _np.asarray(v))
               for k, v in all_feed.items()}
        aux_names = set(self.list_auxiliary_states())
        args = {k: v for k, v in raw.items() if k not in aux_names}
        auxs = {k: v for k, v in raw.items() if k in aux_names}
        missing = [n for n in self.list_inputs() if n not in raw]
        if missing:
            raise MXNetError(f"eval is missing inputs: {missing}")
        run = self._build_eval()
        outs, _ = run(args, auxs, _random.next_key(), training)
        wrapped = [NDArray(o) for o in outs]
        return wrapped[0] if len(wrapped) == 1 else wrapped

    def eval(self, ctx=None, **kwargs):
        out = self.eval_with(kwargs)
        return out if isinstance(out, list) else [out]

    # --------------------------------------------------------------- bind --
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        """Allocate all arguments from input shapes and bind
        (parity: symbol.py:1666)."""
        from ..context import current_context
        from ..executor import Executor
        from ..ndarray import NDArray

        import jax.numpy as jnp

        ctx = ctx or current_context()
        # a context LIST means data-parallel over the group (reference:
        # DataParallelExecutorGroup); arrays start on the primary device
        # and the Executor replicates/shards them over its dp mesh
        primary = ctx[0] if isinstance(ctx, (list, tuple)) else ctx
        shape_hints = {k: v for k, v in kwargs.items()
                       if isinstance(v, (tuple, list))}
        from ..analysis.verify import verify_enabled

        if verify_enabled():
            # pre-bind static checking (MXNET_TPU_VERIFY=0 opts out): a bad
            # kwarg / wiring / shape conflict surfaces here with node-level
            # diagnostics instead of failing inside the XLA trace below
            self.verify(type_dict=type_dict, **shape_hints)
        shapes, dtypes = self._infer(
            shape_hints,
            {k: canonical_dtype(v) for k, v in (type_dict or {}).items()})
        arg_arrays = OrderedDict()
        for name in self.list_arguments():
            key = ("var", name)
            if key not in shapes:
                raise MXNetError(f"simple_bind: shape of {name!r} unknown")
            arg_arrays[name] = NDArray(
                jnp.zeros(shapes[key], dtypes[key]), ctx=primary)
        aux_arrays = OrderedDict()
        for name in self.list_auxiliary_states():
            aux_arrays[name] = NDArray(
                jnp.zeros(shapes["var", name], dtypes["var", name]),
                ctx=primary)
        return Executor(self, ctx, arg_arrays, aux_arrays, grad_req)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **_ignored):
        """Bind with caller-provided arrays (parity: symbol.py bind)."""
        from ..context import current_context
        from ..executor import Executor

        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = OrderedDict(zip(arg_names, args))
        else:
            args = OrderedDict((n, args[n]) for n in arg_names)
        aux_names = self.list_auxiliary_states()
        if aux_states is None:
            aux_states = OrderedDict()
        elif isinstance(aux_states, (list, tuple)):
            aux_states = OrderedDict(zip(aux_names, aux_states))
        else:
            aux_states = OrderedDict((n, aux_states[n]) for n in aux_names)
        return Executor(self, ctx, args, aux_states, grad_req,
                        grad_arrays=args_grad)

    # --------------------------------------------------------------- json --
    def tojson(self):
        order = _topo(self._entries)
        node_index = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            entry = {"op": n.op if n.op else "null", "name": n.name,
                     "inputs": [[node_index[id(c)], oi, 0]
                                for c, oi in n.inputs]}
            if n.attrs:
                entry["attrs"] = {k: _attr_str(v) for k, v in n.attrs.items()}
            nodes.append(entry)
        heads = [[node_index[id(n)], i, 0] for n, i in self._entries]
        arg_nodes = [i for i, n in enumerate(order) if n.is_var]
        return json.dumps(
            {"nodes": nodes, "arg_nodes": arg_nodes, "heads": heads,
             "attrs": {"mxnet_version": ["int", 10800],
                       "framework": ["str", "mxnet_tpu"]}},
            indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ---------------------------------------------------------- operators --
    def __add__(self, other):
        return _binary(self, other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _binary(self, other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, "elemwise_sub", "_rminus_scalar",
                       reverse=True)

    def __mul__(self, other):
        return _binary(self, other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binary(self, other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _binary(self, other, "elemwise_div", "_rdiv_scalar",
                       reverse=True)

    def __neg__(self):
        return _apply_op("_mul_scalar", [self], {"scalar": -1.0})

    def __pow__(self, other):
        if isinstance(other, Symbol):
            return _apply_op("broadcast_power", [self, other], {})
        return _apply_op("_power_scalar", [self], {"scalar": other})

    def __getattr__(self, item):
        """Symbol.relu(), .reshape(...), .sum(...): op-as-method sugar,
        mirroring the generated NDArray methods."""
        if item.startswith("_"):
            raise AttributeError(item)
        try:
            _registry.get(item)
        except KeyError:
            raise AttributeError(item) from None

        def method(*args, **kwargs):
            return _apply_op(item, [self, *args], kwargs)

        method.__name__ = item
        return method


def _output_name(entry):
    node, idx = entry
    if node.is_var:
        return node.name
    if node.num_outputs == 1:
        return f"{node.name}_output"
    return f"{node.name}_output{idx}"


def _attr_str(v):
    return v if isinstance(v, str) else repr(v)


def _parse_attr(s):
    if not isinstance(s, str):
        return s
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _bn_aux_update(node, outs, aux_vals, new_aux):
    """Functional moving-stat update for a training-mode BatchNorm node
    (reference: aux writeback in the executor)."""
    momentum = node.attrs.get("momentum", 0.9)
    _, batch_mean, batch_var = outs[0], outs[1], outs[2]
    for stat, inp_idx in (("mean", 3), ("var", 4)):
        child, _ = node.inputs[inp_idx]
        if not child.is_aux:
            continue
        old = new_aux.get(child.name, aux_vals[child.name])
        batch = batch_mean if stat == "mean" else batch_var
        new_aux[child.name] = (old * momentum
                               + batch.astype(old.dtype) * (1 - momentum))


def _eval_shape_node(node, in_structs):
    import functools

    import jax
    import jax.numpy as jnp

    op = _registry.get(node.op)
    kwargs = _op_kwargs(node)
    sig_names = [p.name for p in _sig_params(op)]
    if "training" in sig_names:
        kwargs["training"] = False
    if "key" in sig_names and "key" not in kwargs:
        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
        fn = functools.partial(op.fn, **kwargs)
        out = jax.eval_shape(lambda k, *a: fn(*a, key=k),
                             key_struct, *in_structs)
    else:
        out = jax.eval_shape(functools.partial(op.fn, **kwargs),
                             *in_structs)
    return out if isinstance(out, (tuple, list)) else (out,)


def _param_shape_rules(node, data_struct):
    """Parameter shapes (and, for quantized ops, dtypes) derivable from
    the (first) data input — the shape-inference rules of the
    reference's layer ops. Values are ``(shape, dtype_or_None)``."""
    if data_struct is None:
        return {}
    dshape = tuple(data_struct.shape)
    attrs = node.attrs
    rules = {}

    def put(idx, shape, dtype=None):
        child, _ = node.inputs[idx]
        if child.is_var:
            rules[child.name] = (tuple(int(s) for s in shape), dtype)

    op = node.op
    if op == "FullyConnected":
        num_hidden = attrs["num_hidden"]
        flatten = attrs.get("flatten", True)
        in_units = (int(_np.prod(dshape[1:])) if flatten else dshape[-1])
        put(1, (num_hidden, in_units))
        if len(node.inputs) > 2:
            put(2, (num_hidden,))
    elif op == "Convolution":
        kernel = attrs.get("kernel", ())
        num_filter = attrs["num_filter"]
        num_group = attrs.get("num_group", 1)
        put(1, (num_filter, dshape[1] // num_group) + tuple(kernel))
        if len(node.inputs) > 2:
            put(2, (num_filter,))
    elif op == "Deconvolution":
        kernel = attrs.get("kernel", ())
        num_filter = attrs["num_filter"]
        num_group = attrs.get("num_group", 1)
        put(1, (dshape[1], num_filter // num_group) + tuple(kernel))
        if len(node.inputs) > 2:
            put(2, (num_filter,))
    elif op in ("BatchNorm", "LeakyReLU"):
        axis = attrs.get("axis", 1)
        channels = dshape[axis if op == "BatchNorm" else 1]
        for i in range(1, len(node.inputs)):
            put(i, (channels,))
    elif op in ("LayerNorm",):
        axis = attrs.get("axis", -1)
        for i in range(1, len(node.inputs)):
            put(i, (dshape[axis],))
    elif op in ("GroupNorm", "InstanceNorm"):
        for i in range(1, len(node.inputs)):
            put(i, (dshape[1],))
    elif op == "Embedding":
        put(1, (attrs["input_dim"], attrs["output_dim"]))
    elif op == "_contrib_quantized_fully_connected":
        num_hidden = attrs["num_hidden"]
        flatten = attrs.get("flatten", True)
        in_units = (int(_np.prod(dshape[1:])) if flatten else dshape[-1])
        put(1, (num_hidden, in_units), "int8")
        # channel-wise scale; a tensor-wise graph carries (1,) params,
        # which bind paths must pass explicitly (eval_with always works)
        put(2, (num_hidden,))
        if len(node.inputs) > 3:
            put(3, (num_hidden,))
    elif op == "_contrib_quantized_conv":
        kernel = attrs.get("kernel", ())
        num_filter = attrs["num_filter"]
        num_group = attrs.get("num_group", 1)
        put(1, (num_filter, dshape[1] // num_group) + tuple(kernel),
            "int8")
        put(2, (num_filter,))
        if len(node.inputs) > 3:
            put(3, (num_filter,))
    elif op == "_contrib_quantized_embedding":
        put(1, (attrs["input_dim"], attrs["output_dim"]), "int8")
        put(2, (1,))
        put(3, (1,))
    elif op == "RNN":
        put(1, (_rnn_param_size(dshape, attrs),))
    elif op in ("SoftmaxOutput", "SVMOutput"):
        # class-index labels: data shape minus the class dim (reference
        # backward shape inference, softmax_output.cc)
        if len(node.inputs) > 1:
            put(1, dshape[:-1])
    elif op in ("LinearRegressionOutput", "LogisticRegressionOutput",
                "MAERegressionOutput"):
        if len(node.inputs) > 1:
            put(1, dshape)  # regression labels match the prediction shape
    return rules


def _rnn_param_size(dshape, attrs):
    """Flat fused-parameter length (parity: rnn-inl.h GetRnnParamSize)."""
    mode = attrs.get("mode", "lstm")
    state_size = attrs["state_size"]
    num_layers = attrs.get("num_layers", 1)
    bidirectional = attrs.get("bidirectional", False)
    ngates = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]
    dirs = 2 if bidirectional else 1
    input_size = dshape[2]
    total = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        per_dir = ngates * state_size * (isz + state_size)  # W_x + W_h
        per_dir += 2 * ngates * state_size                  # b_x + b_h
        total += per_dir * dirs
    return total


# --------------------------------------------------------------------------
# op application / composition
def _as_symbol(x):
    if isinstance(x, Symbol):
        return x
    return None


def _binary(lhs, rhs, elemwise_op, scalar_op, reverse=False):
    if isinstance(rhs, Symbol):
        return _apply_op(elemwise_op, [lhs, rhs], {})
    return _apply_op(scalar_op, [lhs], {"scalar": float(rhs)})


def _resolve_num_outputs(op, n_inputs, attrs):
    """Node output count: static int, or resolved from the node's
    hyper-parameters for dynamic-output ops (split/split_v2/Custom)."""
    n = op.num_outputs
    if callable(n):
        n = n(n_inputs, attrs)
    return n or 1


def _apply_op(op_name, args, kwargs):
    """Build an op node from Symbol args + static kwargs (the compose
    primitive behind every `mx.sym.<op>` wrapper)."""
    op = _registry.get(op_name)
    name = kwargs.pop("name", None)
    kwargs.pop("attr", None)
    sig = _sig_params(op)
    sig_names = [p.name for p in sig]

    # map positional symbols onto signature array slots, in order
    pos_syms = [a for a in args if isinstance(a, Symbol)]
    sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
    static = {k: v for k, v in kwargs.items()
              if not isinstance(v, Symbol) and k not in _RUNTIME_PARAMS}
    # graph-build-time parameter validation + dmlc-style string coercion
    # (symbol JSON attrs arrive as strings) — errors surface at compose
    # time, like dmlc::Parameter::Init in the reference. COPY the result:
    # check_kwargs returns the op's cached validated dict, and node.attrs
    # is mutated later (_set_attr) — sharing would poison the cache
    static = dict(op.check_kwargs(static))

    if name is None:
        from .. import name as _name_mod

        hint = op_name.lower().lstrip("_")
        name = _name_mod.current().get(None, hint)

    layer_params = {p[0]: p for p in _LAYER_PARAMS.get(op.name, ())}
    inputs = []  # (sig_param_name, Symbol-or-None)
    pos_iter = iter(pos_syms)
    for p in sig:
        if p.kind is inspect.Parameter.VAR_KEYWORD:
            continue  # **kwargs catch-all (e.g. Custom) — statics, not inputs
        if p.kind is inspect.Parameter.VAR_POSITIONAL:
            # *arrays slot (concat/add_n/Custom/...): consume EVERY remaining
            # positional symbol here so none spill into scalar-param slots
            for nxt in pos_iter:
                inputs.append((p.name, nxt))
            # keyword Symbol inputs land here too (mx.sym.Custom(data=x)):
            # ordered by the op's declared input names when it declares
            # them, else by keyword order
            if sym_kwargs:
                order = None
                if op.input_names is not None:
                    try:
                        order = [n for n in op.input_names(static)
                                 if n in sym_kwargs]
                    except Exception:
                        order = None
                for k in (order if order is not None else list(sym_kwargs)):
                    inputs.append((p.name, sym_kwargs.pop(k)))
            continue
        if p.name in _RUNTIME_PARAMS or p.name in static:
            continue
        if p.name in sym_kwargs:
            inputs.append((p.name, sym_kwargs.pop(p.name)))
            continue
        nxt = next(pos_iter, None)
        if nxt is not None:
            inputs.append((p.name, nxt))
            continue
        # exhausted explicit inputs: auto-create layer parameter vars
        if p.name in layer_params:
            pname, is_aux, skip = layer_params[p.name]
            if skip is not None and skip(static):
                continue
            inputs.append((p.name, var(f"{name}_{pname}", is_aux=is_aux)))
        elif p.default is inspect.Parameter.empty:
            raise MXNetError(
                f"op {op_name!r} missing required input {p.name!r}")
        else:
            break  # remaining params are statics with defaults
    if sym_kwargs:
        raise MXNetError(f"op {op_name!r}: unexpected symbol inputs "
                         f"{sorted(sym_kwargs)}")

    from .. import attribute as _attribute

    scope_attrs = _attribute.current().get()
    if scope_attrs:  # AttrScope: dunder keys, never op parameters
        static = dict(scope_attrs, **static)
    node = _Node(op.name, name, static,
                 [(s._entries[0][0], s._entries[0][1])
                  for _, s in inputs if s is not None],
                 num_outputs=_resolve_num_outputs(op, len(inputs), static))
    return Symbol([(node, i) for i in range(node.num_outputs)]) \
        if node.num_outputs > 1 else Symbol([(node, 0)])


# --------------------------------------------------------------------------
# public constructors
def var(name, attr=None, shape=None, dtype=None, init=None, is_aux=False,
        **kwargs):
    """A named graph input (parity: symbol.py var/Variable)."""
    from .. import attribute as _attribute

    attrs = _attribute.current().get(attr)
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = _np.dtype(canonical_dtype(dtype)).name
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else repr(init)
    if is_aux:
        attrs["__is_aux__"] = True
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs), 0)])


Variable = var


def Group(symbols):  # noqa: N802 - reference API name
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def zeros(shape, dtype="float32", name=None, **kwargs):
    return _apply_op("_zeros", [], {"shape": shape, "dtype": dtype,
                                    "name": name, **kwargs})


def ones(shape, dtype="float32", name=None, **kwargs):
    return _apply_op("_ones", [], {"shape": shape, "dtype": dtype,
                                   "name": name, **kwargs})


def arange(start, stop=None, step=1.0, dtype="float32", name=None, **kw):
    return _apply_op("_arange", [], {"start": start, "stop": stop,
                                     "step": step, "dtype": dtype,
                                     "name": name, **kw})


def load_json(json_str):
    """Rebuild a Symbol from graph JSON (parity: symbol.py load_json).
    Also accepts reference-produced symbol.json for ops we implement."""
    data = json.loads(json_str)
    raw_nodes = data["nodes"]
    built = []
    for rn in raw_nodes:
        attrs = {k: _parse_attr(v)
                 for k, v in (rn.get("attrs") or rn.get("param") or
                              rn.get("attr") or {}).items()}
        op_name = rn["op"]
        if op_name == "null":
            node = _Node(None, rn["name"], attrs)
        else:
            op = _registry.get(op_name)
            # JSON attrs are the string-valued dmlc params: validate and
            # coerce HERE so a bad attr raises a structured OpParamError
            # at load time, not a TypeError at bind/execution
            from ..attribute import is_dunder

            clean = op.check_kwargs(
                {k: v for k, v in attrs.items() if not is_dunder(k)})
            node = _Node(op.name, rn["name"], {**attrs, **clean})
        built.append(node)
    for rn, node in zip(raw_nodes, built):
        node.inputs = [(built[i], oi) for i, oi, *_ in rn["inputs"]]
        if node.op is not None:
            node.num_outputs = _resolve_num_outputs(
                _registry.get(node.op), len(node.inputs), node.attrs)
    _mark_aux(built)
    heads = data.get("heads")
    if heads:
        entries = [(built[i], oi) for i, oi, *_ in heads]
    else:
        entries = [(built[-1], 0)]
    return Symbol(entries)


def _mark_aux(nodes):
    """Mark aux-state variables by their consumer slots (the reference
    derives this from FMutateInputs; here BatchNorm slots 3/4)."""
    for node in nodes:
        if node.op == "BatchNorm":
            for idx in (3, 4):
                if idx < len(node.inputs):
                    child, _ = node.inputs[idx]
                    if child.is_var:
                        child.attrs["__is_aux__"] = True


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# --------------------------------------------------------------------------
# graph-pass registry (the subgraph-framework analogue)

GRAPH_PASSES = {}


def register_pass(name):
    """Register a named graph pass ``fn(symbol, **kwargs) -> Symbol``
    (parity role: the reference's subgraph-backend registry,
    src/operator/subgraph/subgraph_property.h + MXSetSubgraphPropertyOpt —
    external libraries loaded via mx.library.load can register passes the
    same way lib_api custom passes do)."""

    def deco(fn):
        GRAPH_PASSES[name.lower()] = fn
        return fn

    return deco


def list_passes():
    return sorted(GRAPH_PASSES)


def _symbol_optimize_for(self, backend, args=None, aux=None, ctx=None,
                         **kwargs):
    """parity: symbol.py optimize_for(:1449) — apply a registered backend
    graph pass and return the rewritten Symbol. On TPU the 'default'
    backend is the identity: operator fusion is XLA's job, so the passes
    that carry semantic weight are precision/quantization rewrites (AMP,
    INT8) and user-registered ones."""
    key = (backend or "default").lower()
    try:
        pass_fn = GRAPH_PASSES[key]
    except KeyError:
        raise MXNetError(
            f"unknown backend {backend!r}; registered: {list_passes()}"
        ) from None
    return pass_fn(self, args=args, aux=aux, **kwargs)


Symbol.optimize_for = _symbol_optimize_for


@register_pass("default")
def _default_pass(sym, args=None, aux=None, **kwargs):
    """Fusion/layout belong to XLA — the default backend is the graph
    itself (the reference's default backend likewise returns the graph
    when no property matches)."""
    return sym


@register_pass("amp")
def _amp_pass(sym, args=None, aux=None, target_dtype="bfloat16", **kwargs):
    from .. import amp as _amp

    if args is not None or aux is not None:
        out_sym, _, _ = _amp.convert_model(sym, args or {}, aux or {},
                                           target_dtype=target_dtype)
        return out_sym
    return _amp.convert_symbol(sym, target_dtype=target_dtype) \
        if hasattr(_amp, "convert_symbol") else sym


@register_pass("int8")
def _int8_pass(sym, args=None, aux=None, excluded_sym_names=(),
               ranges=None, **kwargs):
    from ..contrib.quantization import quantize_graph

    return quantize_graph(sym, excluded_sym_names=excluded_sym_names,
                          ranges=ranges)
