"""Logging helpers (parity: python/mxnet/log.py — get_logger with the
colored level formatter the reference's examples configure)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING", "ERROR",
           "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

PY3 = True  # parity constant (reference exports it)


class _Formatter(logging.Formatter):
    """parity: log.py _Formatter — level-colored prefix when the stream
    is a tty, plain otherwise."""

    _COLORS = {logging.WARNING: "\x1b[0;33m", logging.ERROR: "\x1b[0;31m",
               logging.CRITICAL: "\x1b[0;35m", logging.DEBUG: "\x1b[0;34m"}

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        fmt = "%(asctime)s %(levelname)s %(name)s: %(message)s"
        if self.colored and record.levelno in self._COLORS:
            fmt = (self._COLORS[record.levelno] +
                   "%(asctime)s %(levelname)s %(name)s:\x1b[0m %(message)s")
        self._style._fmt = fmt
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """parity: log.py getLogger — a logger with the framework formatter
    attached once."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_handler", None) is None:
        if filename:
            mode = filemode or "a"
            handler = logging.FileHandler(filename, mode)
            handler.setFormatter(_Formatter(colored=False))
        else:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                _Formatter(colored=getattr(sys.stderr, "isatty",
                                           lambda: False)()))
        logger.addHandler(handler)
        logger._mxtpu_handler = handler
    logger.setLevel(level)
    return logger


getLogger = get_logger
