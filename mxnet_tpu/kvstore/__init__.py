"""KVStore (parity: python/mxnet/kvstore/ + src/kvstore/)."""
from . import buckets
from .base import KVStoreBase
from .kvstore import KVStore, PeerLostError, create

__all__ = ["KVStore", "KVStoreBase", "PeerLostError", "buckets",
           "create"]
