"""KVStore (parity: python/mxnet/kvstore/ + src/kvstore/)."""
from .base import KVStoreBase
from .kvstore import KVStore, PeerLostError, create

__all__ = ["KVStore", "KVStoreBase", "PeerLostError", "create"]
