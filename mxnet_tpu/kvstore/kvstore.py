"""KVStore implementations.

Parity target: `KVStore::Create` type strings (`src/kvstore/kvstore.cc:41-83`)
and the local/device/dist semantics:

  local / local_update_cpu / local_allreduce_cpu
      -> single-process aggregation (CommCPU, `src/kvstore/comm.h:103`)
  device / local_allreduce_device / nccl
      -> single-process aggregation on accelerator (CommDevice :451 /
         KVStoreNCCL) — on TPU a jnp sum; multi-chip reduction inside one
         process is XLA's job (ShardedTrainer), so these collapse to one
         in-process implementation with device-side merge
  dist_sync / dist_device_sync / dist_async
      -> multi-host: backed by jax.distributed + psum over all hosts'
         devices. When jax.distributed has not been initialised this is a
         1-worker group (rank 0), matching the reference running dist_*
         without a tracker.

Optimizer-on-store (`set_optimizer`/`update_on_kvstore`, the reference's
server-side `ApplyUpdates`, kvstore_dist_server.h:346) is supported on all
types via an attached Updater.

Gradient compression (2-bit with error feedback,
`src/kvstore/gradient_compression.h`) applies to cross-host traffic; the
API records the setting and the dist path consumes it.
"""
from __future__ import annotations


from .. import optimizer as opt_mod
from ..ndarray import NDArray
from ..preempt import PEERLOST_EXIT_CODE
from ..telemetry import flight as _flight
from ..watchdog import StallError
from .base import KVStoreBase

__all__ = ["KVStore", "PeerLostError", "create", "OP_COUNTS"]

# process-lifetime op totals, read by the telemetry 'kvstore' collector
# at scrape time (mxtpu_kvstore_ops_total{op=...}) — plain dict int
# bumps so the per-push cost is nil; collectives additionally land in
# the flight recorder via their watchdog 'kvstore.sync' spans
OP_COUNTS = {"init": 0, "push": 0, "pull": 0, "barrier": 0,
             "allreduce": 0, "fused": 0}


class PeerLostError(StallError):
    """A cross-host kvstore collective (barrier / all-reduce) missed its
    watchdog deadline — a peer process is presumed dead or wedged.

    Subclasses :class:`~mxnet_tpu.watchdog.StallError` (same
    ``point``/``label``/``elapsed``/``deadline``/``bundle`` attributes —
    the crash bundle is already written when this raises) and adds the
    gang coordinates: ``op`` (the collective), ``rank``, ``num_workers``.
    A gang supervisor catching this can tear down and restart the group
    elastically instead of letting every survivor wedge forever.

    ``exit_code`` (76, the ladder's ``peer-lost`` rung) is what a worker
    that cannot recover should exit with; the gang excepthook installed
    by ``mxnet_tpu.elastic`` maps an *uncaught* PeerLostError onto it
    automatically, so the supervisor sees a reschedulable ladder code
    instead of the interpreter's generic 1.
    """

    exit_code = PEERLOST_EXIT_CODE

    def __init__(self, op, rank, num_workers, stall, census=None):
        super().__init__(stall.point, stall.label, stall.elapsed,
                         stall.deadline, stall.bundle)
        self.op = op
        self.rank = rank
        self.num_workers = num_workers
        #: bucket-pipeline census at the moment of loss (op
        #: 'bucket_reduce' — which fused collectives were in flight);
        #: the same census rides in the crash bundle's report.json
        self.census = census
        _flight.rec("gang.peer_lost", stall.point,
                    f"{op} rank {rank}/{num_workers}")
        self.args = (
            f"kvstore {op!r}: peer lost — rank {rank}/{num_workers} "
            f"waited {stall.elapsed:.1f}s (deadline {stall.deadline:g}s) "
            "for the group; a peer process is presumed dead or wedged"
            + (f"; crash bundle: {stall.bundle}" if stall.bundle else "")
            + (f"; bucket census: {census}" if census else ""),)


def _to_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@KVStoreBase.register
class KVStore(KVStoreBase):
    """In-process store: 'local' and 'device' semantics (parity:
    KVStoreLocal, src/kvstore/kvstore_local.h:121)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = {}
        self._str_keys = False

    @property
    def type(self):
        return self._type

    def is_capable(self, capability):
        return capability == KVStoreBase.OPTIMIZER

    # ------------------------------------------------------------ core ----
    def init(self, key, value):
        OP_COUNTS["init"] += 1
        keys, values = self._canonical(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy() if isinstance(v, NDArray) else NDArray(v)

    @staticmethod
    def _merge(agg, v):
        """Pairwise aggregation; row_sparse pairs merge by row union
        WITHOUT densifying (parity: comm.h ReduceRowSparse)."""
        from ..ndarray.sparse import RowSparseNDArray, sparse_add

        if isinstance(agg, RowSparseNDArray) and \
                isinstance(v, RowSparseNDArray):
            return sparse_add(agg, v)
        return agg + v

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the per-key merge buffer (parity:
        KVStoreLocal::PushImpl + CommDevice::Reduce)."""
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        _watchdog.beat("kvstore.push")  # liveness for hang diagnostics
        OP_COUNTS["push"] += 1
        _faults.point("kvstore.push")  # flaky-gradient-sync injection
        keys, values = self._canonical_push(key, value)
        for k, vals in zip(keys, values):
            agg = vals[0]
            for v in vals[1:]:
                agg = self._merge(agg, v)
            if self._updater is not None:
                # update-on-kvstore: weight := update(weight, agg)
                self._updater(self._key_index(k), agg, self._store[k])
            else:
                self._pending_setdefault(k)
                self._pending[k] = agg if self._pending[k] is None \
                    else self._merge(self._pending[k], agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """parity: KVStoreLocal::PullImpl — copy current value into out."""
        from .. import watchdog as _watchdog

        _watchdog.beat("kvstore.pull")  # liveness for hang diagnostics
        OP_COUNTS["pull"] += 1
        keys, outs = self._canonical(key, out)
        for k, o in zip(keys, outs):
            src = self._value_for_pull(k)
            for target in _to_list(o):
                src.copyto(target)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """parity: kvstore.py row_sparse_pull — pull only selected rows."""
        assert row_ids is not None, "row_ids is required"
        keys, outs = self._canonical(key, out)
        rids = _to_list(row_ids)
        if len(rids) == 1 and len(outs) > 1:
            rids = rids * len(outs)
        for k, o, r in zip(keys, outs, rids):
            src = self._value_for_pull(k)
            rows = src.take(r)
            from ..ndarray.sparse import RowSparseNDArray, row_sparse_array

            for target in _to_list(o):
                if isinstance(target, RowSparseNDArray):
                    target._update(rows, r)
                else:
                    # dense out: scatter selected rows, others zero
                    import jax.numpy as jnp

                    dense = jnp.zeros(src.shape, src._data.dtype)
                    dense = dense.at[r._data.astype("int32")].set(rows._data)
                    target._rebind(dense)

    # ------------------------------------------------ optimizer-on-store ---
    def set_optimizer(self, optimizer):
        """parity: kvstore.py set_optimizer — weights update inside the
        store on push (the reference's optimizer-on-server)."""
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def _key_index(self, key):
        try:
            return int(key)
        except (TypeError, ValueError):
            return key

    def set_gradient_compression(self, compression_params):
        """parity: kvstore.py set_gradient_compression ('2bit', threshold).
        Compression applies to cross-host traffic (dist_* stores); the
        reference likewise ignores it for purely local stores."""
        if not compression_params:
            self._compression = {}  # falsy input disables compression
            return
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype != "2bit":
            raise ValueError(f"unsupported gradient compression {ctype!r}; "
                             "only '2bit' is implemented (parity: "
                             "gradient_compression.cc)")
        params.setdefault("threshold", 0.5)
        self._compression = params

    @property
    def gradient_compression(self):
        return dict(self._compression)

    # ------------------------------------------------------------- misc ---
    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        OP_COUNTS["barrier"] += 1
        from .. import engine

        engine.wait_all()

    def _barrier(self):
        self.barrier()

    # --------------------------------------------------------- plumbing ---
    def _canonical(self, key, value):
        keys = _to_list(key)
        if value is None:
            return keys, [None] * len(keys)
        values = _to_list(value)
        if len(keys) == 1 and len(values) > 1 and not isinstance(values[0],
                                                                (list, tuple)):
            values = [values]
        assert len(keys) == len(values), f"{len(keys)} keys vs {len(values)} values"
        return keys, values

    def _canonical_push(self, key, value):
        keys = _to_list(key)
        values = _to_list(value)
        if len(keys) == 1:
            # single key: value may be one array or a list to aggregate
            if isinstance(value, (list, tuple)) and len(values) > 1 \
                    and isinstance(values[0], NDArray):
                return keys, [list(values)]
            return keys, [[values[0]] if not isinstance(values[0], list)
                          else values[0]]
        grouped = []
        for v in values:
            grouped.append(list(_to_list(v)))
        assert len(keys) == len(grouped)
        return keys, grouped

    def _pending_setdefault(self, k):
        if not hasattr(self, "_pending"):
            self._pending = {}
        self._pending.setdefault(k, None)

    def _value_for_pull(self, k):
        if k not in self._store:
            raise ValueError(f"key {k!r} has not been initialized")
        pending = getattr(self, "_pending", {}).pop(k, None)
        if pending is not None:
            # merge pending pushes into the stored value (sync semantics)
            self._store[k]._rebind((self._store[k] + pending)._data) \
                if self._updater is None and self._type.startswith("dist") \
                else self._store[k]._rebind(pending._data)
        return self._store[k]


from ..base import maybe_init_distributed as _maybe_init_distributed


class _DistKVStore(KVStore):
    """Multi-host store over jax.distributed (parity: KVStoreDist,
    src/kvstore/kvstore_dist.h:44 — push aggregates across workers, pull
    returns the aggregate; sync mode barriers each step).

    Launched workers rendezvous via the MXTPU_COORDINATOR /
    MXTPU_NUM_WORKERS / MXTPU_WORKER_ID env set by tools/launch.py.
    Without an initialised jax.distributed runtime this degenerates to a
    single-worker group, exactly like running the reference's dist_sync
    without a tracker spawning peers.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import jax

        _maybe_init_distributed()
        self._procs = jax.process_count()
        self._rank = jax.process_index()
        self._residuals = {}  # error-feedback buffers for 2bit compression
        # collective-order deadlock detector (analysis.distcheck pass 2):
        # every collective this rank issues is fingerprinted; barrier()
        # cross-checks the fingerprints so rank-divergent schedules raise
        # a structured error BEFORE they can wedge a real collective
        from ..analysis import distcheck as _distcheck

        self._sched = _distcheck.ScheduleRecorder() \
            if _distcheck.enabled() else None
        # bucketed async gradient reduction (docs/PERFORMANCE.md):
        # pushes stage into size-capped buckets, each reduced as ONE
        # fused async collective resolved at pull/barrier; bucket cap 0
        # restores the legacy per-key path exactly
        from . import buckets as _buckets

        cap = _buckets.bucket_bytes()
        self._pipeline = _buckets.BucketPipeline(self, cap) if cap > 0 \
            else None

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._procs

    def init(self, key, value):
        super().init(key, value)
        if self._pipeline is None:
            return
        from ..ndarray.sparse import RowSparseNDArray

        keys, _ = self._canonical(key, value)
        for k in keys:
            stored = self._store[k]
            if isinstance(stored, RowSparseNDArray):
                continue  # sparse traffic keeps the row-union path
            self._pipeline.register(k, tuple(stored.shape),
                                    str(stored._data.dtype))

    def _bucketed(self, agg):
        """True when this push rides the bucket pipeline (dense,
        registered, and there is actually a group to reduce over — or
        the force knob engages the full path single-process)."""
        if self._pipeline is None:
            return False
        from . import buckets as _buckets
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(agg, RowSparseNDArray):
            return False
        return self._procs > 1 or _buckets.bucket_force()

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store across all workers.

        ``priority`` keeps the MXNet contract (higher reduces earlier):
        the bucket pipeline realizes it structurally — assembly is keyed
        on registration order and a bucket dispatches the moment its
        last member is pushed, so pushing in backward order (what gluon
        ``Trainer`` does, mirroring the reference's ``priority=-index``)
        reduces last-layer buckets first while earlier layers are still
        computing. The argument itself is accepted for API parity.
        """
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        _watchdog.beat("kvstore.push")  # liveness across the collective
        _faults.point("kvstore.push")  # flaky-gradient-sync injection
        keys, values = self._canonical_push(key, value)
        for k, vals in zip(keys, values):
            agg = vals[0]
            for v in vals[1:]:
                agg = self._merge(agg, v)
            if self._bucketed(agg) and self._pipeline.wants(k):
                gather = self._type == "dist_async" \
                    and self._updater is not None
                # compression applies to CROSS-HOST traffic only (the
                # 1-proc force seam stages raw, like the legacy path)
                if self._compression and not gather and self._procs > 1:
                    codes, meta = self._quantize(k, agg)
                    self._pipeline.enqueue(k, codes.reshape(-1), meta)
                else:
                    self._pipeline.enqueue(
                        k, agg._data.reshape(-1),
                        {"shape": tuple(agg.shape),
                         "dtype": str(agg._data.dtype)})
                continue
            if self._sched is not None:
                # the static collective schedule this rank is committing
                # to: op kind + key + payload signature (divergent key
                # ORDER across ranks is the classic silent deadlock)
                self._sched.note(
                    "allgather" if self._type == "dist_async"
                    else "allreduce",
                    f"{k}:{tuple(agg.shape)}:{agg.dtype}")
            if self._procs > 1 and self._type == "dist_async" \
                    and self._updater is not None:
                self._async_push(k, agg)
                continue
            if self._procs > 1:
                from ..ndarray.sparse import RowSparseNDArray

                if self._compression and \
                        not isinstance(agg, RowSparseNDArray):
                    # sparse grads bypass compression (reference parity:
                    # GradientCompression supports dense only; compressing
                    # would densify and defeat sparse storage)
                    agg = self._compressed_cross_host_sum(k, agg)
                else:
                    agg = self._cross_host_sum(agg)
            if self._updater is not None:
                self._updater(self._key_index(k), agg, self._store[k])
            else:
                self._pending_setdefault(k)
                self._pending[k] = agg if self._pending[k] is None \
                    else self._merge(self._pending[k], agg)

    def _async_push(self, k, agg):
        """dist_async optimizer-on-store semantics (parity:
        kvstore_dist_server.h:325-346 ApplyUpdates in async mode): every
        worker's push is a SEPARATE optimizer step on the store — N pushes
        mean N updates, not one update on the summed gradient. The updates
        are applied in rank order on every worker, which keeps replicas
        bit-identical while preserving the async statistical semantics
        (the reference's server applies them in arrival order instead).

        This is the legacy (unbucketed) path — one blocking
        ``process_allgather`` per key, O(N·size) on the wire. With
        bucketing enabled the same gather rides ONE fused bucket
        collective instead (``_dispatch_bucket`` mode ``gather``)."""
        import time as _time

        from ..ndarray import NDArray
        from jax.experimental.multihost_utils import process_allgather
        from ..telemetry import steps as _tsteps

        t0 = _time.monotonic()
        gathered = process_allgather(agg._data)  # (procs, ...) per-worker
        _tsteps.phase("sync", (_time.monotonic() - t0) * 1e3)
        idx = self._key_index(k)
        for r in range(self._procs):
            self._updater(idx, NDArray(gathered[r]), self._store[k])

    # ------------------------------------------------- bucket pipeline ----
    def _bucket_mode(self):
        """The fused-collective flavour for a dispatching bucket:
        ``gather`` for dist_async optimizer-on-store (every worker's
        payload applied separately), ``sum`` otherwise (2-bit codes sum
        exactly like raw grads — they concatenate trivially and rescale
        per key at resolve)."""
        if self._type == "dist_async" and self._updater is not None:
            return "gather"
        return "sum"

    def _note_bucket(self, mode, sig):
        """Collective-order fingerprint entry for one fused dispatch —
        rank-identical because bucket assembly is keyed on registration
        order (distcheck pass 2 cross-checks at the next barrier)."""
        if self._sched is not None:
            self._sched.note("allgather" if mode == "gather"
                             else "allreduce", sig)

    def _dispatch_bucket(self, raw, mode):
        """Asynchronously dispatch ONE fused cross-host collective over
        a flattened bucket payload and return the (unresolved) future
        array — the caller resolves it later under the ``kvstore.sync``
        watchdog point. Nothing here blocks the host; that is the whole
        point."""
        OP_COUNTS["fused"] += 1
        if mode == "gather":
            return self._dispatch_gather(raw)
        OP_COUNTS["allreduce"] += 1
        return self._dispatch_sum(raw)

    def _dispatch_sum(self, raw):
        """Async fused cross-host sum (the bucketed twin of
        ``_cross_host_sum`` — same mesh, same reduction, no host
        block)."""
        import jax.numpy as jnp

        try:
            from jax.experimental import multihost_utils
            from jax.sharding import PartitionSpec

            mesh = self._proc_mesh()
            stacked = multihost_utils.host_local_array_to_global_array(
                raw[None], mesh, PartitionSpec("proc"))  # noqa: partition-spec-literal — the deliberate per-PROCESS reduction axis (baselined for the legacy path)
            summed = self._sum_exe(mesh)(stacked)
            return multihost_utils.global_array_to_host_local_array(
                summed, mesh, PartitionSpec())
        except (ValueError, RuntimeError, TypeError):
            # fallback: allgather + local sum (blocking, still correct)
            from jax.experimental.multihost_utils import process_allgather

            return jnp.sum(jnp.asarray(process_allgather(raw)), axis=0)

    def _gather_exe(self, mesh):
        """Cached compiled cross-process allgather (identity with a
        replicated output layout), through the unified compile service."""
        exe = getattr(self, "_gather_exe_cache", None)
        if exe is None:
            from jax.sharding import NamedSharding, PartitionSpec

            from .. import compile as _compile

            exe = _compile.jit(
                lambda a: a, site="kvstore",
                token=("kvstore", "bucket_gather", f"p{self._procs}"),
                out_shardings=NamedSharding(mesh, PartitionSpec()))
            self._gather_exe_cache = exe
        return exe

    def _dispatch_gather(self, raw):
        """Async fused allgather: returns a ``(procs, total)`` future so
        N workers' dist_async updates ride ONE gathered bucket instead
        of one blocking ``process_allgather`` per key."""
        import jax.numpy as jnp

        try:
            from jax.experimental import multihost_utils
            from jax.sharding import PartitionSpec

            mesh = self._proc_mesh()
            stacked = multihost_utils.host_local_array_to_global_array(
                raw[None], mesh, PartitionSpec("proc"))  # noqa: partition-spec-literal — the deliberate per-PROCESS reduction axis (baselined for the legacy path)
            gathered = self._gather_exe(mesh)(stacked)
            return multihost_utils.global_array_to_host_local_array(
                gathered, mesh, PartitionSpec())
        except (ValueError, RuntimeError, TypeError):
            from jax.experimental.multihost_utils import process_allgather

            return jnp.asarray(process_allgather(raw))

    def _apply_reduced(self, k, piece, mode, meta):
        """Scatter one key's slice of a resolved bucket back into the
        store — the same per-key apply the legacy path runs, so the
        bucketed pipeline is numerically bit-identical to it."""
        from ..ndarray import NDArray

        shape = meta["shape"]
        if mode == "gather":
            idx = self._key_index(k)
            for r in range(self._procs):
                self._updater(idx, NDArray(piece[r].reshape(shape)),
                              self._store[k])
            return
        if meta.get("thr") is not None:
            # summed 2-bit codes rescale to the original dtype
            from .. import kernels as _kernels

            agg = NDArray(_kernels.dispatch(
                "twobit_decompress", piece.reshape(shape), meta["thr"],
                dtype=meta["dtype"]))
        else:
            agg = NDArray(piece.reshape(shape))
        if self._updater is not None:
            self._updater(self._key_index(k), agg, self._store[k])
        else:
            self._pending_setdefault(k)
            self._pending[k] = agg if self._pending[k] is None \
                else self._merge(self._pending[k], agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Resolve any in-flight bucket reductions covering `key`
        first (futures resolve here, at barrier, or at optimizer
        apply), then the normal pull."""
        if self._pipeline is not None:
            for k in _to_list(key):
                self._pipeline.resolve(k)
        super().pull(key, out=out, priority=priority,
                     ignore_sparse=ignore_sparse)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._pipeline is not None:
            for k in _to_list(key):
                self._pipeline.resolve(k)
        super().row_sparse_pull(key, out=out, priority=priority,
                                row_ids=row_ids)

    def _proc_mesh(self):
        """One-device-per-process mesh (cached): the reduction axis spans
        processes, whatever the per-host device count."""
        import jax

        mesh = getattr(self, "_mesh_cache", None)
        if mesh is None:
            import numpy as _onp
            from jax.sharding import Mesh

            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[i] for i in sorted(by_proc)]
            mesh = Mesh(_onp.array(devs), ("proc",))
            self._mesh_cache = mesh
        return mesh

    def _sum_exe(self, mesh):
        """Cached compiled cross-process reduction."""
        exe = getattr(self, "_sum_exe_cache", None)
        if exe is None:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec

            exe = jax.jit(lambda a: jnp.sum(a, axis=0),
                          out_shardings=NamedSharding(mesh,
                                                      PartitionSpec()))
            self._sum_exe_cache = exe
        return exe

    def _cross_host_sum(self, value):
        """All-reduce across hosts as ONE XLA reduction over a global
        process mesh — O(size) transfer (reduce-scatter/all-gather chosen
        by XLA over DCN/ICI), not the O(N*size) of an allgather+sum.

        Deadline-bounded: the whole collective runs under the
        ``kvstore.sync`` watchdog point, so a dead peer surfaces as a
        structured :class:`PeerLostError` (crash bundle attached) instead
        of wedging this worker forever."""
        import time as _time

        from .. import faults as _faults
        from .. import watchdog as _watchdog
        from ..telemetry import steps as _tsteps

        OP_COUNTS["allreduce"] += 1

        def _reduce():
            import jax.numpy as jnp

            # injectable ('kvstore.sync' hang == a peer stopped reducing)
            _faults.point("kvstore.sync")
            raw = value._data
            try:
                from jax.experimental import multihost_utils
                from jax.sharding import PartitionSpec

                mesh = self._proc_mesh()
                stacked = multihost_utils.host_local_array_to_global_array(
                    raw[None], mesh, PartitionSpec("proc"))
                summed = self._sum_exe(mesh)(stacked)
                return NDArray(
                    multihost_utils.global_array_to_host_local_array(
                        summed, mesh, PartitionSpec()))
            except (ValueError, RuntimeError, TypeError):
                # fallback: allgather + local sum (still correct, more bytes)
                from jax.experimental.multihost_utils import process_allgather

                gathered = process_allgather(raw)
                return NDArray(jnp.sum(gathered, axis=0))

        t0 = _time.monotonic()
        try:
            return _watchdog.sync(
                "kvstore.sync", _reduce,
                label=f"cross_host_sum rank {self._rank}/{self._procs}")
        except StallError as e:
            raise PeerLostError("cross_host_sum", self._rank, self._procs,
                                e) from e
        finally:
            # the per-key host cost of the serialized legacy path lands
            # in the step timeline's 'sync' phase (the bucketed pipeline
            # records only its blocked resolve tail there instead)
            _tsteps.phase("sync", (_time.monotonic() - t0) * 1e3)

    def _quantize(self, key, value):
        """2-bit quantization with error feedback (parity:
        `src/kvstore/gradient_compression.h:38-134` / .cc Quantize2Bit):
        grad+residual quantizes to {-1, 0, +1} (int8 on the wire — 4x
        fewer bytes than f32) and the quantization error carries into
        the next step's residual. Returns ``(codes, meta)`` — the
        resolve-side rescale needs the threshold and original dtype.
        Shared by the legacy per-key path and bucket fusion (codes
        concatenate trivially and sum exactly like raw grads)."""
        import jax.numpy as jnp

        thr = float(self._compression.get("threshold", 0.5))
        raw = value._data
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros_like(raw)
        # fused add-residual + threshold-quantize + residual-out in one
        # pass (registry family twobit_compress; XLA baseline is the
        # same compare/select/multiply soup this used to inline)
        from .. import kernels as _kernels

        codes, new_res = _kernels.dispatch("twobit_compress", raw, res,
                                           thr)
        self._residuals[key] = new_res
        return codes, {"shape": tuple(raw.shape),
                       "dtype": str(raw.dtype), "thr": thr}

    def _compressed_cross_host_sum(self, key, value):
        """Legacy per-key compressed reduction: quantize, ONE all-reduce
        of the codes, rescale by the threshold (bucketing fuses the same
        codes across keys instead)."""
        codes, meta = self._quantize(key, value)
        summed = self._cross_host_sum(NDArray(codes))._data
        from .. import kernels as _kernels

        return NDArray(_kernels.dispatch("twobit_decompress", summed,
                                         meta["thr"],
                                         dtype=meta["dtype"]))

    def barrier(self):
        """Cross-host rendezvous, deadline-bounded via the
        ``kvstore.sync`` watchdog point: a peer that never arrives turns
        the wait into :class:`PeerLostError` (with crash bundle) instead
        of an unbounded wedge, so a gang supervisor can restart the group
        elastically.

        When distcheck is enabled the barrier first cross-checks every
        rank's collective-schedule fingerprint (a fixed-shape allgather,
        deadlock-free even when the schedules diverged): ranks that
        issued different collective sequences raise a structured
        :class:`~mxnet_tpu.analysis.distcheck.CollectiveOrderError`
        naming the divergence, instead of wedging in the NEXT collective
        and surfacing only as a PeerLostError after the deadline."""
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        OP_COUNTS["barrier"] += 1
        if self._pipeline is not None:
            # flush: dispatch every still-staged bucket (descending
            # registration order) and resolve all in-flight futures —
            # the barrier is a resolution point, and the fingerprints
            # compared below must include every issued collective
            self._pipeline.resolve(None)
        if self._sched is not None:
            if self._procs > 1:
                from ..analysis import distcheck as _distcheck

                _distcheck.cross_check_schedule(self._sched, kv=self)
            self._sched.note("barrier", "")

        def _rendezvous():
            # injectable ('kvstore.sync' hang == a peer died pre-barrier)
            _faults.point("kvstore.sync")
            if self._procs > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("kvstore_barrier")

        try:
            _watchdog.sync(
                "kvstore.sync", _rendezvous,
                label=f"barrier rank {self._rank}/{self._procs}")
        except StallError as e:
            raise PeerLostError("barrier", self._rank, self._procs,
                                e) from e
        super().barrier()


def create(name="local"):
    """parity: kvstore.py create / KVStore::Create (kvstore.cc:41-83)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    lname = name.lower()
    if lname in KVStoreBase.kv_registry and lname not in ("kvstore",):
        return KVStoreBase.kv_registry[lname](
        ) if lname != "kvstore" else KVStore(lname)
    if lname in ("local", "local_update_cpu", "local_allreduce_cpu",
                 "device", "local_allreduce_device", "nccl"):
        return KVStore(lname)
    if lname in ("dist_sync", "dist_device_sync", "dist_async",
                 "dist_sync_device", "dist"):
        return _DistKVStore(lname)
    raise ValueError(f"unknown KVStore type {name!r}")
