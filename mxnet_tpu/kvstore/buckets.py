"""Bucketed, priority-ordered, async cross-host gradient reduction.

The legacy ``_DistKVStore.push`` host-blocks on ONE collective per key in
push order — the ``sync`` phase of the PR 9 step timeline is a dead
serial tail after backward. This module is the overlap pipeline that
hides it (the reference hides the same cost with priority-ordered async
pushes through the dependency engine + ps-lite, SURVEY §L2/L7):

* **Bucketing** — pushed gradients are flattened and staged into
  size-capped buckets (``MXNET_TPU_BUCKET_BYTES``, default 4 MiB; ``0``
  restores the legacy per-key path exactly). Bucket assembly is a pure
  function of *registration order* (the ``init`` sequence), never of
  push arrival order, so every rank builds the identical plan and the
  distcheck pass-2 collective fingerprint stays rank-identical.
* **Priority / overlap** — a bucket dispatches its ONE fused collective
  the moment its last member arrives (backward pushes complete
  last-registered buckets first, so last-layer grads reduce while
  earlier layers are still computing); buckets still staged at a flush
  point dispatch in descending registration order (the MXNet
  ``priority=-index`` contract). Dispatch is JAX async — nothing blocks.
* **Resolution** — futures resolve at ``pull`` / ``barrier`` /
  optimizer-apply under the existing ``kvstore.sync`` watchdog point:
  a dead peer still surfaces as a structured
  :class:`~mxnet_tpu.kvstore.PeerLostError` (now carrying the bucket
  census, which also rides in the crash bundle), and only the *blocked*
  tail of each collective is accounted as ``sync`` time in the step
  timeline — the overlapped remainder is the win the
  ``mxtpu_kvstore_overlap_ratio`` gauge reports.

``MXNET_TPU_BUCKET_FORCE=1`` engages the pipeline even in a 1-process
group (the collective degenerates to identity) — the single-process
test/chaos seam for the full stage→fuse→dispatch→resolve path.
"""
from __future__ import annotations

import os
import threading
import time
import weakref

__all__ = ["DEFAULT_BUCKET_BYTES", "bucket_bytes", "bucket_force",
           "BucketPlan", "BucketPipeline", "census", "comm_stats"]

DEFAULT_BUCKET_BYTES = 4 << 20  # ~4 MiB, the classic DDP bucket size

#: process-lifetime pipelines (weak — dropped with their kvstore), read
#: by the telemetry collector, tools/diagnose.py and crash bundles
_LIVE: "weakref.WeakSet[BucketPipeline]" = weakref.WeakSet()


def bucket_bytes():
    """Effective bucket cap in bytes (``MXNET_TPU_BUCKET_BYTES``;
    0 disables bucketing — the legacy per-key collective path)."""
    raw = os.environ.get("MXNET_TPU_BUCKET_BYTES")
    if not raw:
        return DEFAULT_BUCKET_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_BUCKET_BYTES


def bucket_force():
    """True when ``MXNET_TPU_BUCKET_FORCE=1`` engages the pipeline even
    for a 1-process group (tests / chaos drills)."""
    return os.environ.get("MXNET_TPU_BUCKET_FORCE") == "1"


class BucketPlan:
    """Deterministic key → bucket assignment, keyed on registration
    order alone.

    Keys are appended greedily in ``init`` order: a key joins the
    newest bucket iff the dtype matches and the bucket stays under the
    byte cap, else it opens the next bucket. An oversized single
    gradient therefore gets a bucket of its own (and never blocks other
    keys from fusing). The assignment is stable under append — earlier
    buckets never change when new keys register — and identical on
    every rank that runs the same ``init`` sequence.
    """

    def __init__(self, cap_bytes):
        self.cap = int(cap_bytes)
        self.order = []    # keys, registration order
        self.info = {}     # key -> {shape, dtype, nelems, nbytes, bucket}
        self.buckets = []  # [{bid, keys, nbytes, dtype}]

    def register(self, key, shape, dtype):
        """Add `key` (idempotent). Returns its bucket id."""
        if key in self.info:
            return self.info[key]["bucket"]
        import numpy as _np

        shape = tuple(int(d) for d in shape)
        nelems = 1
        for d in shape:
            nelems *= d
        dtype = str(dtype)
        nbytes = nelems * _np.dtype(dtype).itemsize
        if self.buckets and self.buckets[-1]["dtype"] == dtype \
                and self.buckets[-1]["nbytes"] + nbytes <= self.cap:
            b = self.buckets[-1]
        else:
            b = {"bid": len(self.buckets), "keys": [], "nbytes": 0,
                 "dtype": dtype}
            self.buckets.append(b)
        b["keys"].append(key)
        b["nbytes"] += nbytes
        self.order.append(key)
        self.info[key] = {"shape": shape, "dtype": dtype,
                          "nelems": nelems, "nbytes": nbytes,
                          "bucket": b["bid"]}
        return b["bid"]

    def describe(self):
        return {"cap_bytes": self.cap, "keys": len(self.order),
                "buckets": [{"bid": b["bid"], "keys": len(b["keys"]),
                             "bytes": b["nbytes"], "dtype": b["dtype"]}
                            for b in self.buckets]}


class _InFlight:
    """One dispatched (not yet resolved) fused collective."""

    __slots__ = ("bid", "seq", "keys", "meta", "future", "mode", "nbytes",
                 "partial", "t_stage0", "t_fuse", "t_dispatch")

    def __init__(self, bid, seq, keys, meta, future, mode, nbytes,
                 partial, t_stage0, t_fuse, t_dispatch):
        self.bid = bid
        self.seq = seq
        self.keys = keys
        self.meta = meta
        self.future = future
        self.mode = mode
        self.nbytes = nbytes
        self.partial = partial
        self.t_stage0 = t_stage0
        self.t_fuse = t_fuse
        self.t_dispatch = t_dispatch


class BucketPipeline:
    """The staging/dispatch/resolve state machine for one dist kvstore.

    The owning store provides the collective hooks (duck-typed, so tests
    drive the pipeline with a stub):

    ``_bucket_mode()``            -> "sum" | "gather"
    ``_dispatch_bucket(raw, mode)`` -> future array (async dispatch)
    ``_apply_reduced(key, piece, mode, meta)``  scatter-back per key
    ``_note_bucket(mode, sig)``   collective-schedule fingerprint note
    ``rank`` / ``num_workers``    gang coordinates for error messages
    """

    def __init__(self, kv, cap_bytes):
        self._kv = kv
        self.plan = BucketPlan(cap_bytes)
        self._staged = {}    # bid -> {"vals": {k: raw}, "meta": {k: meta},
        #                             "t0": monotonic of first stage}
        self._inflight = []  # FIFO of _InFlight
        self._lock = threading.RLock()
        self._seq = 0
        self.stats = {"fused": 0, "keys": 0, "bytes": 0, "partial": 0,
                      "drains": 0, "resolved": 0,
                      "wait_ms": 0.0, "window_ms": 0.0, "max_pending": 0}
        _LIVE.add(self)

    # ------------------------------------------------------------ intake --
    def register(self, key, shape, dtype):
        with self._lock:
            return self.plan.register(key, shape, dtype)

    def wants(self, key):
        """True when `key` rides the bucket pipeline (registered at
        ``init``; unregistered keys keep the legacy per-key path)."""
        return key in self.plan.info

    def enqueue(self, key, raw, meta):
        """Stage one key's flattened payload; the bucket dispatches its
        fused collective the moment the last member arrives. A repeat
        push of a key whose bucket has not resolved yet first drains
        that bucket (legacy per-push semantics — every push is its own
        reduction round), which every rank hits at the same point."""
        with self._lock:
            bid = self.plan.info[key]["bucket"]
            st = self._staged.get(bid)
            if st is not None and key in st["vals"]:
                self.stats["drains"] += 1
                self._dispatch(bid)
                self._resolve_where(lambda inf: inf.bid == bid)
                st = None
            if st is None:
                st = self._staged[bid] = {"vals": {}, "meta": {},
                                          "t0": time.monotonic()}
            st["vals"][key] = raw
            st["meta"][key] = meta
            if len(st["vals"]) == len(self.plan.buckets[bid]["keys"]):
                self._dispatch(bid)

    # ---------------------------------------------------------- dispatch --
    def _dispatch(self, bid):
        st = self._staged.pop(bid, None)
        if st is None:
            return
        import jax.numpy as jnp

        bucket = self.plan.buckets[bid]
        keys = [k for k in bucket["keys"] if k in st["vals"]]
        partial = len(keys) < len(bucket["keys"])
        t_fuse = time.monotonic()
        parts = [st["vals"][k] for k in keys]
        fused = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        nbytes = int(fused.size) * fused.dtype.itemsize
        kv = self._kv
        mode = kv._bucket_mode()
        # the fingerprint entry every rank must agree on: bucket id +
        # member census + payload signature (registration-order keys)
        sig = (f"bucket{bid}:{len(keys)}keys:{int(fused.size)}:"
               f"{fused.dtype}" + ("?partial" if partial else ""))
        kv._note_bucket(mode, sig)
        future = kv._dispatch_bucket(fused, mode)
        self._seq += 1
        inf = _InFlight(bid, self._seq, keys, dict(st["meta"]), future,
                        mode, nbytes, partial, st["t0"], t_fuse,
                        time.monotonic())
        self._inflight.append(inf)
        self.stats["fused"] += 1
        self.stats["keys"] += len(keys)
        self.stats["bytes"] += nbytes
        if partial:
            self.stats["partial"] += 1
        self.stats["max_pending"] = max(self.stats["max_pending"],
                                        len(self._inflight))
        from ..telemetry import flight as _flight

        _flight.rec("kvstore.bucket.dispatch", "kvstore.sync",
                    f"bucket {bid} seq {inf.seq}: {len(keys)} keys, "
                    f"{nbytes}B, {mode}")

    # ----------------------------------------------------------- resolve --
    def resolve(self, key=None):
        """Resolve pending reductions: for `key`, the bucket holding it;
        for None (barrier / explicit flush), everything. Buckets still
        staged dispatch first, highest priority (latest-registered)
        first, so the flush order is a pure function of the plan."""
        with self._lock:
            if not self._staged and not self._inflight:
                return
            if key is not None and not self.wants(key):
                return
            for bid in sorted(self._staged, reverse=True):
                if key is None or bid == self.plan.info[key]["bucket"]:
                    self._dispatch(bid)
            if key is None:
                self._resolve_where(lambda inf: True)
            else:
                want = self.plan.info[key]["bucket"]
                self._resolve_where(lambda inf: inf.bid == want)

    def _resolve_where(self, pred):
        remaining = []
        for inf in self._inflight:  # FIFO = dispatch order
            if pred(inf):
                self._resolve_one(inf)
            else:
                remaining.append(inf)
        self._inflight = remaining

    def _resolve_one(self, inf):
        from .. import faults as _faults
        from .. import watchdog as _watchdog

        kv = self._kv
        t0 = time.monotonic()

        def _block():
            import jax

            # injectable: a 'kvstore.sync' hang == a peer stopped
            # reducing mid-bucket
            _faults.point("kvstore.sync")
            return jax.block_until_ready(inf.future)  # noqa: unbounded-sync — bounded by the enclosing watchdog.sync

        try:
            arr = _watchdog.sync(
                "kvstore.sync", _block,
                label=f"bucket {inf.bid} seq {inf.seq} "
                      f"({len(inf.keys)} keys, {inf.nbytes}B) rank "
                      f"{kv.rank}/{kv.num_workers}")
        except _watchdog.StallError as e:
            from .kvstore import PeerLostError

            err = PeerLostError("bucket_reduce", kv.rank, kv.num_workers,
                                e, census=self.describe())
            raise err from e
        now = time.monotonic()
        wait_ms = (now - t0) * 1e3
        window_ms = max((now - inf.t_dispatch) * 1e3, wait_ms)
        self.stats["resolved"] += 1
        self.stats["wait_ms"] += wait_ms
        self.stats["window_ms"] += window_ms
        off = 0
        for k in inf.keys:
            n = self.plan.info[k]["nelems"]
            kv._apply_reduced(k, arr[..., off:off + n], inf.mode,
                              inf.meta.get(k))
            off += n
        # only the BLOCKED tail is sync time in the step timeline — the
        # in-flight remainder overlapped compute (that is the headline)
        from ..telemetry import flight as _flight, steps as _tsteps

        _tsteps.phase("sync", wait_ms)
        _flight.rec("kvstore.bucket.resolve", "kvstore.sync",
                    f"bucket {inf.bid} seq {inf.seq}: waited "
                    f"{wait_ms:.2f}ms of {window_ms:.2f}ms in flight")
        self._trace(inf, t0, now, wait_ms)

    def _trace(self, inf, t_resolve, t_done, wait_ms):
        """Bucket lifecycle spans (enqueue→fuse→dispatch→resolve) for
        the PR 12 tracing plane — merged gang traces show the reduction
        window overlapping backward per rank."""
        from ..telemetry import trace as _trace

        if not _trace.enabled():
            return
        tid = f"kvbucket-{inf.bid}-{inf.seq}"
        lane = 300 + (inf.bid % 100)
        parent = _trace.commit(
            f"kvstore.bucket[{inf.bid}]", inf.t_stage0,
            (t_done - inf.t_stage0) * 1e3, kind="bucket", trace_id=tid,
            lane=lane,
            attrs={"keys": len(inf.keys), "bytes": inf.nbytes,
                   "mode": inf.mode, "partial": inf.partial,
                   "wait_ms": round(wait_ms, 3)})
        for name, a, b in (
                ("enqueue", inf.t_stage0, inf.t_fuse),
                ("fuse", inf.t_fuse, inf.t_dispatch),
                ("dispatch", inf.t_dispatch, t_resolve),
                ("resolve", t_resolve, t_done)):
            _trace.commit(name, a, max(0.0, (b - a) * 1e3), kind="phase",
                          trace_id=tid, parent=parent, lane=lane)

    # -------------------------------------------------------- inspection --
    @property
    def overlap_ratio(self):
        """1 - blocked/in-flight over the pipeline lifetime (1.0 = the
        collectives fully hid behind compute; None before any resolve)."""
        w = self.stats["window_ms"]
        if w <= 0.0:
            return None
        return round(max(0.0, 1.0 - self.stats["wait_ms"] / w), 4)

    def pending(self):
        # deliberately lock-free: the crash-bundle writer reads the
        # census from ANOTHER thread while the resolving thread may be
        # wedged inside watchdog.sync still holding the pipeline lock —
        # an advisory snapshot must never deadlock the post-mortem
        staged = dict(self._staged)
        return {"staged": {bid: len(st["vals"])
                           for bid, st in staged.items()},
                "inflight": len(self._inflight)}

    def describe(self):
        """JSON-able census (diagnose / crash bundles / PeerLostError).
        Lock-free by design — see :meth:`pending`."""
        return {"plan": self.plan.describe(),
                "pending": self.pending(),
                "stats": dict(self.stats),
                "overlap_ratio": self.overlap_ratio}


# ------------------------------------------------------- module-level views --

def census():
    """Per-pipeline censuses of every live bucket pipeline (crash
    bundles, tools/diagnose.py)."""
    return [p.describe() for p in list(_LIVE)]


def comm_stats():
    """Aggregate gradient-comms stats over live pipelines — the
    telemetry collector's source for ``mxtpu_kvstore_overlap_ratio`` /
    fused-collective counters, and the bench.py train-line fields."""
    agg = {"fused": 0, "keys": 0, "bytes": 0, "partial": 0, "drains": 0,
           "resolved": 0, "wait_ms": 0.0, "window_ms": 0.0, "pending": 0,
           "max_pending": 0, "pipelines": 0}
    for p in list(_LIVE):
        st = p.stats
        agg["pipelines"] += 1
        for k in ("fused", "keys", "bytes", "partial", "drains",
                  "resolved", "wait_ms", "window_ms"):
            agg[k] += st[k]
        agg["max_pending"] = max(agg["max_pending"], st["max_pending"])
        agg["pending"] += p.pending()["inflight"]
    agg["wait_ms"] = round(agg["wait_ms"], 3)
    agg["window_ms"] = round(agg["window_ms"], 3)
    agg["overlap_ratio"] = (
        round(max(0.0, 1.0 - agg["wait_ms"] / agg["window_ms"]), 4)
        if agg["window_ms"] > 0 else None)
    return agg
