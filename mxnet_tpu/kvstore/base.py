"""KVStoreBase: the pluggable store interface.

Parity target: `python/mxnet/kvstore/base.py:74,220` — the abstract
init/push/pull/pushpull/broadcast surface plus `KVStoreBase.register`, the
mechanism by which external backends (the reference lists 'horovod',
'byteps') plug in. Here the same registry carries 'local'/'device' (in-
process), 'dist_*' (jax.distributed-backed), and any user backend.
"""
from __future__ import annotations

__all__ = ["KVStoreBase"]


class KVStoreBase:
    """Abstract key-value store (parity: kvstore/base.py:KVStoreBase)."""

    kv_registry = {}

    @staticmethod
    def register(klass):
        """Register a kvstore backend under its lowercased class name
        (parity: base.py:432)."""
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    # -- capability strings (parity: base.py OPTIMIZER/...) -----------------
    OPTIMIZER = "optimizer"

    def is_capable(self, capability):
        raise NotImplementedError

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def type(self):
        return type(self).__name__.lower()
