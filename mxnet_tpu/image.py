"""Image decode / augmentation utilities.

Parity target: `python/mxnet/image/image.py` (pure-Python ImageIter +
augmenters) and the C++ decode path (`src/io/image_recordio_2.cc` — OMP
JPEG decode). Host-side decode uses PIL (libjpeg-turbo under the hood).

Augmentation runs numpy-native: every helper/augmenter is polymorphic
(NDArray in -> NDArray out for API parity; numpy in -> numpy out), and the
batch pipeline stays on host until ONE device transfer per assembled batch.
"""
from __future__ import annotations

import io as _io
import random as _pyrandom

import numpy as _np

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "ImageIter",
           "CreateAugmenter", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "RandomGrayAug", "ImageDetIter",
           "DetAugmenter", "DetHorizontalFlipAug", "DetBorderAug",
           "CreateDetAugmenter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def _like(src, out_np):
    """Return out_np as the same container type as src."""
    if isinstance(src, NDArray):
        return nd.array(out_np, dtype=out_np.dtype)
    return out_np


def _decode_np(buf, flag=1, to_rgb=True):
    from PIL import Image

    img = Image.open(_io.BytesIO(buf if isinstance(buf, (bytes, bytearray))
                                 else bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = _np.asarray(img)[..., None]
    else:
        img = img.convert("RGB")
        arr = _np.asarray(img)
        if not to_rgb:
            arr = arr[..., ::-1]  # BGR like OpenCV default
    return arr.copy()


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an encoded image to an HWC uint8 NDArray (parity:
    mx.image.imdecode)."""
    return nd.array(_decode_np(buf, flag, to_rgb), dtype=_np.uint8)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def _resize_np(arr, w, h):
    from .gluon.data.vision.transforms import _resize_hwc

    return _resize_hwc(arr, (w, h))


def imresize(src, w, h, interp=1):
    return _like(src, _resize_np(_to_np(src), w, h))


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (parity: image.py resize_short)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return _like(src, _resize_np(arr, new_w, new_h))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        arr = _resize_np(arr, size[0], size[1])
    return _like(src, arr)


def _crop_np(arr, x0, y0, w, h, size=None):
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[0], size[1])
    return out


def center_crop(src, size, interp=2):
    arr = _to_np(src)  # converted once; crop on the numpy view
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = int((w - new_w) / 2)
    y0 = int((h - new_h) / 2)
    return _like(src, _crop_np(arr, x0, y0, new_w, new_h)), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = _pyrandom.randint(0, max(0, w - new_w))
    y0 = _pyrandom.randint(0, max(0, h - new_h))
    return _like(src, _crop_np(arr, x0, y0, new_w, new_h)), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(_np.float32)
    if mean is not None:
        arr = arr - _to_np(mean)
    if std is not None:
        arr = arr / _to_np(std)
    return _like(src, arr)


class Augmenter:
    """parity: image.py Augmenter base. Polymorphic: numpy in -> numpy out."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(),
                           {k: v for k, v in self._kwargs.items()
                            if isinstance(v, (int, float, str, list, tuple,
                                              bool, type(None)))}])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _like(src, _to_np(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, NDArray):
            return src.astype(self.typ)
        return _np.asarray(src, dtype=self.typ)


class ColorNormalizeAug(Augmenter):
    """parity: image.py ColorNormalizeAug."""

    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else _np.asarray(_to_np(mean),
                                                          _np.float32)
        self.std = None if std is None else _np.asarray(_to_np(std),
                                                        _np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    """parity: image.py RandomGrayAug."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src)
            gray = arr.astype(_np.float32) @ _np.array([0.299, 0.587, 0.114],
                                                       _np.float32)
            return _like(src, _np.repeat(gray[..., None], 3,
                                         axis=-1).astype(arr.dtype))
        return src


class _JitterAug(Augmenter):
    """Wrap a gluon vision transform as an image Augmenter (numpy-safe)."""

    def __init__(self, transform, **kwargs):
        super().__init__(**kwargs)
        self._t = transform

    def __call__(self, src):
        out = self._t(nd.array(_to_np(src)) if not isinstance(src, NDArray)
                      else src)
        return _to_np(out) if not isinstance(src, NDArray) else out


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """parity: image.py CreateAugmenter — the standard augmentation list,
    honouring every argument (resize/crop/mirror/color jitter/pca/gray/
    normalize)."""
    from .gluon.data.vision import transforms as T

    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(_JitterAug(T.RandomResizedCrop(
            (crop_size[0], crop_size[1]))))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(_JitterAug(T.ColorJitter(brightness, contrast,
                                                saturation)))
    if hue:
        auglist.append(_JitterAug(T.RandomHue(hue)))
    if pca_noise > 0:
        auglist.append(_JitterAug(T.RandomLighting(pca_noise)))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Pure-python image iterator over .rec or .lst+folder (parity:
    python/mxnet/image/image.py ImageIter).

    The final partial batch is padded to full batch_size with wrapped
    samples and `pad` reports the filler count, exactly like the reference
    — so batch shape is constant (no XLA recompilation on the last batch)
    and pad-aware consumers can slice filler off.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        from .io import DataDesc

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape,
                                      _np.float32)]
        self.provide_label = [DataDesc(label_name, (batch_size, label_width),
                                       _np.float32)]
        self.imgrec = None
        self.imglist = None
        if path_imgrec:
            from . import recordio

            idx_path = path_imgrec[:path_imgrec.rfind(".")] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self.seq = list(self.imgrec.keys)
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = _np.asarray(parts[1:-1], dtype=_np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = list(self.imglist.keys())
            self.path_root = path_root
        else:
            raise ValueError("Either path_imgrec or path_imglist is required")
        self.cur = 0
        self.reset()

    def reset(self):
        if self._shuffle:
            _pyrandom.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        """Return (label, numpy HWC image) for the next sample."""
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            from . import recordio

            header, img_bytes = recordio.unpack(self.imgrec.read_idx(idx))
            return header.label, _decode_np(img_bytes)
        label, fname = self.imglist[idx]
        import os

        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, _decode_np(f.read())

    # hooks overridden by ImageDetIter (shared batch-assembly loop below)
    def _empty_label_batch(self):
        return _np.zeros((self.batch_size, self.label_width), _np.float32)

    def _process_sample(self, arr, label):
        """Augment one sample; returns (HWC image, per-sample label row)."""
        for aug in self.auglist:
            arr = aug(arr)
        return arr, label

    def next(self):
        from .io import DataBatch

        c, h, w = self.data_shape
        batch_data = _np.zeros((self.batch_size, h, w, c), _np.float32)
        batch_label = self._empty_label_batch()
        i = 0
        while i < self.batch_size:
            try:
                label, arr = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                break
            arr, label = self._process_sample(arr, label)
            arr = _to_np(arr)
            if arr.shape[:2] != (h, w):
                arr = _resize_np(arr, w, h)
            batch_data[i] = arr.astype(_np.float32)
            batch_label[i] = label
            i += 1
        pad = self.batch_size - i
        if pad:  # wrap-pad to keep a constant batch shape (ref semantics)
            for j in range(pad):
                batch_data[i + j] = batch_data[j % max(i, 1)]
                batch_label[i + j] = batch_label[j % max(i, 1)]
        # ONE device transfer for the whole batch
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        label = nd.array(batch_label)
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()


# ---------------------------------------------------------------------------
# object-detection iterator (parity: python/mxnet/image/detection.py)
class DetAugmenter:
    """Detection augmenter: transforms (image, boxes) jointly
    (parity: detection.py:40 DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    """Random horizontal flip of image AND normalized boxes
    (parity: detection.py:116)."""

    def __init__(self, p):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _to_np(src)[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetBorderAug(DetAugmenter):
    """Pad to a square canvas with probability `p`, rescaling boxes
    (parity: detection.py DetRandomPadAug, simplified geometry)."""

    def __init__(self, fill=127, p=1.0):
        self.fill = fill
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() >= self.p:
            return src, label
        arr = _to_np(src)
        h, w = arr.shape[:2]
        s = max(h, w)
        if h == w:
            return src, label
        out = _np.full((s, s, arr.shape[2]), self.fill, arr.dtype)
        y0, x0 = (s - h) // 2, (s - w) // 2
        out[y0:y0 + h, x0:x0 + w] = arr
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] * w + x0) / s
        label[valid, 3] = (label[valid, 3] * w + x0) / s
        label[valid, 2] = (label[valid, 2] * h + y0) / s
        label[valid, 4] = (label[valid, 4] * h + y0) / s
        return out, label


class _DetImageAug(DetAugmenter):
    """Wrap an image-only Augmenter for detection pipelines (geometry-
    preserving augmenters only: resize/cast/normalize)."""

    def __init__(self, aug):
        self.aug = aug

    def __call__(self, src, label):
        return self.aug(src), label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,
                       std=None, fill=127, rand_pad=0, **kwargs):
    """Detection augmenter pipeline (parity: detection.py:242
    CreateDetAugmenter — the geometry-changing crop family is reduced to
    pad+flip; photometric augs reuse the classification Augmenters).
    Unsupported reference arguments raise instead of silently skipping
    the requested augmentation."""
    if kwargs:
        raise ValueError(
            f"unsupported CreateDetAugmenter arguments {sorted(kwargs)}; "
            "supported: resize, rand_mirror, mean, std, fill, rand_pad")
    auglist = []
    if resize > 0:
        auglist.append(_DetImageAug(ResizeAug(resize)))
    if rand_pad > 0:
        auglist.append(DetBorderAug(fill, p=rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(_DetImageAug(CastAug()))
    if mean is not None or std is not None:
        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53])
        if std is True:
            std = _np.array([58.395, 57.12, 57.375])
        auglist.append(_DetImageAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: variable-object bbox labels padded to a fixed
    (max_objects, label_width) tensor per image, -1 class id marking
    filler rows (parity: detection.py:625 ImageDetIter).

    Per-sample labels are either flat ``k*5`` floats
    ``[cls, xmin, ymin, xmax, ymax] * k`` (normalized coords) or the
    reference's packed format ``[header_width, object_width, ...,
    objects...]`` (detection.py _parse_label). The fixed label shape
    keeps XLA signatures constant across batches.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, data_name="data", label_name="label",
                 label_shape=None, **kwargs):
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=aug_list if aug_list is not None
                         else CreateDetAugmenter(data_shape),
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        from .io import DataDesc

        if label_shape is None:
            label_shape = self._discover_label_shape()
        self.label_shape = tuple(label_shape)
        self.provide_label = [DataDesc(
            label_name, (batch_size,) + self.label_shape, _np.float32)]

    @staticmethod
    def _parse_label(raw):
        """Flat floats -> (k, width) array (parity: detection.py:744)."""
        raw = _np.asarray(raw, _np.float32).ravel()
        if raw.size >= 2 and float(raw[0]).is_integer() and \
                float(raw[1]).is_integer() and 2 <= raw[1] <= 32 and \
                raw[0] >= 2 and (raw.size - raw[0]) % raw[1] == 0:
            header, width = int(raw[0]), int(raw[1])
            body = raw[header:]
        elif raw.size % 5 == 0:
            width, body = 5, raw
        else:
            raise ValueError(f"cannot parse detection label of size "
                             f"{raw.size}")
        return body.reshape(-1, width)

    def _iter_raw_labels(self):
        """All labels WITHOUT decoding any image (labels are in memory
        for .lst sources and in the record headers for .rec)."""
        if self.imglist is not None:
            for label, _ in self.imglist.values():
                yield label
        else:
            from . import recordio

            for idx in self.seq:
                header, _ = recordio.unpack(self.imgrec.read_idx(idx))
                yield header.label

    def _discover_label_shape(self):
        max_obj, width = 1, 5
        for label in self._iter_raw_labels():
            parsed = self._parse_label(label)
            max_obj = max(max_obj, parsed.shape[0])
            width = max(width, parsed.shape[1])
        return (max_obj, width)

    def reshape(self, data_shape=None, label_shape=None):
        """parity: detection.py reshape."""
        from .io import DataDesc

        if data_shape is not None:
            self.data_shape = tuple(data_shape)
            self.provide_data = [DataDesc(
                self.provide_data[0].name,
                (self.batch_size,) + self.data_shape, _np.float32)]
        if label_shape is not None:
            self.label_shape = tuple(label_shape)
            self.provide_label = [DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + self.label_shape, _np.float32)]

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators' label shapes to the elementwise max
        (parity: detection.py sync_label_shape)."""
        assert isinstance(it, ImageDetIter)
        train, val = self.label_shape, it.label_shape
        shape = (max(train[0], val[0]), max(train[1], val[1]))
        self.reshape(label_shape=shape)
        it.reshape(label_shape=shape)
        return it

    # hooks consumed by the shared ImageIter.next batch-assembly loop
    def _empty_label_batch(self):
        return _np.full((self.batch_size,) + self.label_shape, -1.0,
                        _np.float32)

    def _process_sample(self, arr, label):
        max_obj, width = self.label_shape
        parsed = self._parse_label(label)
        if parsed.shape[0] > max_obj or parsed.shape[1] > width:
            raise ValueError(
                f"sample label shape {parsed.shape} exceeds label_shape "
                f"{self.label_shape}; pass a larger label_shape (or use "
                "sync_label_shape) — boxes are never silently dropped")
        full = _np.full((max_obj, width), -1.0, _np.float32)
        full[:parsed.shape[0], :parsed.shape[1]] = parsed
        for aug in self.auglist:
            arr, full = aug(arr, full)
        return arr, full
