"""Module: intermediate-level symbolic training interface.

Parity target: `python/mxnet/module/module.py` — `bind` (:364 →
DataParallelExecutorGroup), `init_params` (:264), `init_optimizer` (:474,
kvstore decision table in model.py:84), forward/backward/update, and the
save_checkpoint/load path (model.py:403-476).

TPU-native: one Executor holds the whole graph as a single XLA executable
(no per-device executor group — data parallelism on TPU is mesh sharding,
`parallel/ShardedTrainer`, not executor replication). The kvstore is still
honored for optimizer-on-store semantics and API parity.
"""
from __future__ import annotations

import logging

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import cpu
from ..initializer import InitDesc
from ..io.io import DataDesc
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    """parity: module/module.py:50."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        if context is None:
            context = cpu()
        # a list of contexts means data parallelism over the group; the
        # Executor turns it into a dp mesh + ONE SPMD executable (GSPMD
        # replacement for DataParallelExecutorGroup, executor_group.py:144)
        self._context = context
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None
        self._inputs_need_grad = False

    # -------------------------------------------------------------- bind --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = [_as_desc(d, self._data_names, i)
                             for i, d in enumerate(data_shapes)]
        self._label_shapes = [_as_desc(d, self._label_names, i)
                              for i, d in enumerate(label_shapes or [])]
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        shape_kwargs = {d.name: tuple(d.shape) for d in self._data_shapes}
        shape_kwargs.update(
            {d.name: tuple(d.shape) for d in self._label_shapes})
        req = {}
        for name in self._param_names:
            if name in self._fixed_param_names or not for_training:
                req[name] = "null"
            elif isinstance(grad_req, dict):
                req[name] = grad_req.get(name, "write")
            else:
                req[name] = grad_req
        if inputs_need_grad:
            for name in self._data_names:
                req[name] = "write"
        self._exec = self._symbol.simple_bind(self._context, grad_req=req,
                                              **shape_kwargs)
        if shared_module is not None and shared_module._exec is not None:
            for name, arr in shared_module._exec.arg_dict.items():
                if name in self._exec.arg_dict and \
                        name in shared_module._param_names:
                    # share storage: identical handles across buckets
                    self._exec.arg_dict[name] = arr
            for name, arr in shared_module._exec.aux_dict.items():
                self._exec.aux_dict[name] = arr
            for name, arr in shared_module._exec.grad_dict.items():
                if name in self._exec.grad_dict:
                    self._exec.grad_dict[name] = arr
        self.binded = True

    # ------------------------------------------------------------ params --
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            src = (arg_params or {}).get(name)
            if src is not None:
                _set_like(arr, src)
            elif self.params_initialized and not force_init:
                pass
            elif initializer is not None:
                init_arr = initializer(InitDesc(name), arr.shape,
                                       dtype=str(arr.dtype))
                _set_like(arr, init_arr)
            elif not allow_missing:
                raise MXNetError(f"parameter {name!r} has no initializer "
                                 "and no provided value")
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            src = (aux_params or {}).get(name)
            if src is not None:
                _set_like(arr, src)
        if arg_params and allow_extra is False:
            extra = set(arg_params) - set(self._param_names)
            if extra:
                raise MXNetError(f"extra parameters: {sorted(extra)}")
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # --------------------------------------------------------- optimizer --
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        if kvstore and self._exec._mesh is not None:
            # context-list (dp mesh) Modules: XLA already all-reduced the
            # gradients inside the SPMD executable, so a single-process
            # kvstore would only re-aggregate what is already global (the
            # reference needs it for multi-GPU, executor_group + kvstore;
            # GSPMD subsumes it). Cross-process stores still matter but
            # hold primary-device copies incompatible with mesh arrays.
            name = kvstore if isinstance(kvstore, str) else kvstore.type
            if str(name).startswith("dist"):
                raise MXNetError(
                    "Module with a context list cannot use a dist kvstore;"
                    " use parallel.ShardedTrainer (dp axis over all hosts)"
                    " for multi-host data parallelism")
            kvstore = None
        if kvstore:
            from .. import kvstore as kv_mod

            if isinstance(kvstore, str):
                kvstore = kv_mod.create(kvstore)
            self._kvstore = kvstore
            self._update_on_kvstore = kvstore.is_capable("optimizer")
            if self._update_on_kvstore:
                kvstore.set_optimizer(optimizer)
            for idx, name in enumerate(self._param_names):
                kvstore.init(name, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    # ----------------------------------------------------------- execute --
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        labels = data_batch.label or []
        for name, arr in zip(self._label_names, labels):
            if name in self._exec.arg_dict:
                feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """parity: model.py:154 _update_params_on_kvstore.

        Off-kvstore updates are batched into ONE multi-tensor executable
        (Optimizer.fused_update_multi) instead of a per-parameter loop —
        a train step costs a single update dispatch.
        """
        assert self.optimizer_initialized
        if self._kvstore is not None and self._update_on_kvstore:
            for name in self._param_names:
                grad = self._exec.grad_dict.get(name)
                if grad is None:
                    continue
                self._kvstore.push(name, grad)
                self._kvstore.pull(name, out=self._exec.arg_dict[name])
            return
        indices, grads, weights = [], [], []
        for idx, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            if self._kvstore is not None:
                self._kvstore.push(name, grad)
                self._kvstore.pull(name, out=grad)
            indices.append(idx)
            grads.append(grad)
            weights.append(self._exec.arg_dict[name])
        if indices:
            self._updater.update_multi(indices, grads, weights)

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self._inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, monitor):
        monitor.install(self._exec)

    # -------------------------------------------------------- checkpoint --
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """parity: module.py save_checkpoint → model.save_checkpoint."""
        from .. import model as model_mod

        arg, aux = self.get_params()
        model_mod.save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    def save_optimizer_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """parity: module.py Module.load."""
        from .. import model as model_mod

        sym, args, auxs = model_mod.load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (args, auxs)
        mod._preload_opt_states = (f"{prefix}-{epoch:04d}.states"
                                   if load_optimizer_states else None)
        return mod

    def _maybe_preloaded(self):
        return getattr(self, "_preloaded", None)

    # -------------------------------------------------------- properties --
    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def label_names(self):
        return list(self._label_names)

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return [(n, tuple(o.shape)) for n, o in
                zip(self.output_names, self._exec.outputs)] \
            if self._exec and self._exec.outputs else None

    def init_params_from_preload(self, initializer=None):
        pre = self._maybe_preloaded()
        if pre is not None:
            self.init_params(initializer=initializer, arg_params=pre[0],
                             aux_params=pre[1], force_init=True)
            if getattr(self, "_preload_opt_states", None):
                self.load_optimizer_states(self._preload_opt_states)

    def fit(self, train_data, **kwargs):
        """fit honoring Module.load's preloaded params (parity:
        base_module.fit arg_params plumbing)."""
        pre = self._maybe_preloaded()
        if pre is not None and "arg_params" not in kwargs:
            kwargs["arg_params"] = pre[0]
            kwargs["aux_params"] = pre[1]
            kwargs.setdefault("allow_missing", False)
        return super().fit(train_data, **kwargs)


def _as_desc(d, names, i):
    if isinstance(d, DataDesc):
        return d
    if isinstance(d, tuple) and len(d) == 2 and isinstance(d[0], str):
        return DataDesc(d[0], tuple(d[1]))
    name = names[i] if i < len(names) else f"input{i}"
    return DataDesc(name, tuple(d))


def _set_like(dst, src):
    """Write src into dst matching dtype and placement (initializers
    produce host values; executor arrays stay on their context device)."""
    from ..ndarray import NDArray, array

    dst._rebind_like(src if isinstance(src, NDArray) else array(src))
