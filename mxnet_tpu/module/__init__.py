"""mx.mod — Module API (parity: python/mxnet/module/)."""
from .base_module import BaseModule, BatchEndParam
from .bucketing_module import BucketingModule
from .module import Module

__all__ = ["BaseModule", "BatchEndParam", "BucketingModule", "Module"]
