"""BaseModule: the high-level train/score/predict driver.

Parity target: `python/mxnet/module/base_module.py` — `fit` (:409, the
full epoch/batch training loop with metric, callbacks and checkpointing),
`score` (:213), `predict` (:320), `forward_backward` (:193).

The intermediate-API contract (bind / init_params / init_optimizer /
forward / backward / update / update_metric) is identical; concrete
modules implement those against the TPU executor.
"""
from __future__ import annotations

import logging
import time
from collections import namedtuple

from .. import metric as metric_mod
from ..base import MXNetError

__all__ = ["BaseModule", "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, (list, tuple)) else [obj]


class BaseModule:
    """parity: module/base_module.py:65."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------ to implement --
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # -------------------------------------------------------- composites --
    def forward_backward(self, data_batch):
        """parity: base_module.py:193."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """parity: base_module.py:213."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            for cb in _as_list(batch_end_callback):
                cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
        for cb in _as_list(score_end_callback):
            cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """parity: base_module.py:320 — returns merged NDArray(s)."""
        from .. import ndarray as nd

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outs = [o[0:o.shape[0] - pad].copy() for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            for outs in output_list:
                if len(outs) != num_outputs:
                    raise MXNetError(
                        "Cannot merge batches: different number of outputs")
            merged = [nd.concat(*[o[i] for o in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The reference training loop (parity: base_module.py:409).

        Preemption-aware: with the :mod:`mxnet_tpu.preempt` handlers
        installed (explicitly or via ``MXNET_TPU_PREEMPT``), a SIGTERM
        lets the in-flight batch finish, runs the ``epoch_end_callback``
        chain once for the current (partial) epoch — that is where
        ``mx.callback.do_checkpoint`` saves — and exits with the
        reschedule code (default 75)."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod
        from .. import preempt as _preempt

        _preempt.maybe_install_from_env()

        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params,
                            force_init=force_init)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
                nbatch += 1
                if _preempt.requested():
                    self.logger.warning(
                        "Epoch[%d] Batch[%d]: preemption drain requested; "
                        "checkpointing and exiting for reschedule",
                        epoch, nbatch)
                    arg_p, aux_p = self.get_params()
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_p, aux_p)
                    # the callback chain just checkpointed: skip the
                    # last-resort hook, only record + exit
                    _preempt.drain(save=False)

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p, allow_missing=False,
                            force_init=True, allow_extra=False)
            for cb in _as_list(epoch_end_callback):
                cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    def install_monitor(self, monitor):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # ------------------------------------------------------------- misc ---
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError
