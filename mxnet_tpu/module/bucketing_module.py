"""BucketingModule: variable-length training via per-bucket executables.

Parity target: `python/mxnet/module/bucketing_module.py:40` — a
`sym_gen(bucket_key) -> (symbol, data_names, label_names)` factory, one
Module per bucket, all sharing parameter storage with the default bucket.

TPU-native: each bucket is a separate XLA executable specialisation (the
shape-keyed compile cache), and weight sharing is literal — the bucket
executors hold the SAME NDArray handles, so there is no parameter copy on
bucket switch (the reference shares memory via shared_module binding).
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """parity: module/bucketing_module.py:40."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_config = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    # -------------------------------------------------------------- bind --
    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind=False, shared_module=None, grad_req=grad_req)
        self._buckets = {self._default_bucket_key: mod}
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """parity: bucketing_module.py switch_bucket — bind a new bucket
        sharing parameter storage with the default bucket."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes, self.for_training,
                     force_rebind=False,
                     shared_module=self._buckets[self._default_bucket_key],
                     grad_req="write")
            if self.params_initialized:
                mod.params_initialized = True
            if self.optimizer_initialized and self._opt_config:
                mod.init_optimizer(**self._opt_config)
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # ----------------------------------------------------------- plumbing --
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        self._buckets[self._default_bucket_key].init_params(
            initializer, arg_params, aux_params, allow_missing, force_init,
            allow_extra)
        for key, mod in self._buckets.items():
            mod.params_initialized = True
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        self._opt_config = dict(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)
        for mod in self._buckets.values():
            mod.init_optimizer(**self._opt_config)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key",
                             self._default_bucket_key)
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, monitor):
        for mod in self._buckets.values():
            mod.install_monitor(monitor)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes
