"""Model bus: live weight streaming from a training gang into a
serving fleet, with poison rejection and rollback.

The reference framework's ``dist_async`` ps-lite mode existed so
recommender-style systems could push weight updates continuously instead
of redeploying; our fleet's only weight path so far was the
whole-generation ``fleet.rollout()``. The bus closes that gap with a
shared-directory pub/sub channel:

* **Publisher** — :meth:`ShardedTrainer.publish_to(bus, every=K)
  <mxnet_tpu.parallel.sharded_trainer.ShardedTrainer.publish_to>` writes
  a version-stamped update record every K steps. Small params ride as
  full tensors; large (embedding-table-shaped) params ride int8
  per-row compressed or top-k sparse rows. A non-finite update (the
  nan-guard signal) is NEVER published — the finite gate runs before
  the record is encoded.
* **Record discipline** — the payload (one ``.update`` npz) lands via
  the checkpoint module's atomic tmp+fsync+rename write; the manifest
  (``.json``, carrying CRC32/size + a per-param shape/dtype census) is
  written *after* it, so a manifest's presence proves a complete
  payload. Torn manifests are skipped (warn-once latch + counter),
  never trusted.
* **Subscriber** — a :class:`BusWatcher` on each serving worker
  validates an incoming version (CRC, census vs the live
  :class:`~mxnet_tpu.serving.model.ServedModel`, finiteness) and
  applies it between batches via ``ServedModel.swap_params`` — shapes
  unchanged, so the compiled bucket ladder survives with ZERO
  recompiles (only ``device_put`` of new buffers). A failing version is
  **quarantined** (a ``reject-v*.json`` record the publisher and
  supervisor can see) and the last good version stays pinned.
* **Rollback** — per the ROADMAP contract, rollback is re-publication:
  :meth:`ModelBus.auto_rollback` re-publishes the newest good version
  as a fresh (higher) version once the head of the bus is quarantined,
  so every subscriber converges back onto known-good weights.

Staleness contract: a subscriber is at most ``K * poll`` behind the
trainer in steady state; the distance is exported as
``mxtpu_serving_model_age_steps`` (latest published step minus applied
step). Versions only move forward — a watcher never applies a version
at or below the one it is serving.

Fault drills: ``modelbus.publish`` fires inside :meth:`ModelBus.publish`
AFTER the finite gate (its ``nan`` mode poisons the first parameter of
the record — simulated in-transit corruption the subscriber must
reject); ``modelbus.apply`` fires on the subscriber's raw payload bytes
(``corrupt`` flips bytes the CRC check must catch, ``delay``/``hang``
stall the apply path). See ``tools/chaos_smoke.py`` phase 14.
"""
from __future__ import annotations

import io
import json
import os
import threading
import time
import weakref
import zlib

import numpy as _np

from . import checkpoint as _checkpoint
from . import faults as _faults
from . import log as _log
from .telemetry import flight as _flight

__all__ = ["ModelBus", "BusWatcher", "decode_update", "stats",
           "live_watchers", "DEFAULT_COMPRESS_THRESHOLD",
           "PAYLOAD_SUFFIX", "MANIFEST_SUFFIX"]

_logger = _log.get_logger("mxnet_tpu.modelbus")

PAYLOAD_SUFFIX = ".update"
MANIFEST_SUFFIX = ".json"

# params at or above this many elements ride int8-compressed by default
DEFAULT_COMPRESS_THRESHOLD = 65536

# process-lifetime totals behind mxtpu_modelbus_*_total (telemetry
# export's pull collector reads them; see telemetry/export.py)
STATS = {"published": 0, "applied": 0, "rejected": 0, "rollbacks": 0,
         "publish_skipped_nonfinite": 0, "torn_skips": 0,
         "stale_skips": 0}
_stats_lock = threading.Lock()

_WATCHERS = weakref.WeakSet()

# warn-once latch (the kernels-fallback convention): one log line per
# bus directory however many torn records are skipped; the counter
# keeps the true total
_torn_warned = set()


def _bump(key, n=1):
    with _stats_lock:
        STATS[key] = STATS.get(key, 0) + n


def stats():
    """Process-lifetime bus totals (the telemetry collector's source)."""
    with _stats_lock:
        return dict(STATS)


def live_watchers():
    """BusWatcher instances alive in this process (diagnose, the
    telemetry collector)."""
    return list(_WATCHERS)


class _StaleRecord(Exception):
    """A record that cannot be applied YET (sparse base mismatch, payload
    mid-rotation) — skip without quarantining it."""


# ------------------------------------------------------ record encoding ---

def _is_finite(arr):
    return arr.dtype.kind != "f" or bool(_np.isfinite(arr).all())


def _encode_param(arr, encoding, key, out, base=None, k=None):
    """Encode one array into npz entries under `key`; returns the extra
    census fields for the manifest entry."""
    if encoding == "full":
        out[key] = arr
        return {}
    if encoding == "int8_rows":
        rows = arr.reshape(arr.shape[0], -1)
        m = _np.max(_np.abs(rows), axis=1)
        scale = _np.where(m > 0, m / 127.0, 1.0).astype(_np.float32)
        out[key + "_q"] = _np.clip(
            _np.rint(rows / scale[:, None]), -127, 127).astype(_np.int8)
        out[key + "_s"] = scale
        return {}
    if encoding == "topk_rows":
        delta = _np.linalg.norm(
            (arr - base).reshape(arr.shape[0], -1), axis=1)
        k = min(int(k), arr.shape[0])
        idx = _np.sort(_np.argpartition(delta, -k)[-k:]).astype(_np.int64)
        out[key + "_idx"] = idx
        out[key + "_rows"] = arr[idx]
        return {"rows": int(k)}
    raise ValueError(f"unknown bus encoding {encoding!r}")


def _decode_param(ent, npz, key, base=None):
    dtype = _np.dtype(ent["dtype"])
    shape = tuple(ent["shape"])
    enc = ent["encoding"]
    if enc == "full":
        arr = _np.asarray(npz[key])
    elif enc == "int8_rows":
        q = _np.asarray(npz[key + "_q"])
        scale = _np.asarray(npz[key + "_s"])
        arr = (q.astype(_np.float32) * scale[:, None]).reshape(shape)
    elif enc == "topk_rows":
        if base is None:
            raise ValueError(
                "topk_rows record needs the base parameter values "
                f"(base_version) to decode {ent.get('name')!r}")
        arr = _np.array(base, copy=True)
        arr[_np.asarray(npz[key + "_idx"])] = _np.asarray(
            npz[key + "_rows"])
    else:
        raise ValueError(f"unknown bus encoding {enc!r}")
    if tuple(arr.shape) != shape:
        raise ValueError(
            f"decoded shape {arr.shape} != census shape {shape} for "
            f"{ent.get('name')!r}")
    return arr.astype(dtype, copy=False)


def decode_update(manifest, payload, base_params=None):
    """Decode one bus record into ``(params, aux)`` lists of numpy
    arrays in manifest order. `payload` is the raw ``.update`` bytes or
    an open npz mapping; `base_params` (manifest-ordered current values)
    is required only for ``topk_rows`` entries.

    This is the ONE decode seam: the watcher's compressed-row apply and
    a manual full-tensor apply both pass through it, which is what makes
    them bit-equal by construction (tests/test_modelbus.py asserts it).
    """
    if isinstance(payload, (bytes, bytearray)):
        payload = _np.load(io.BytesIO(bytes(payload)), allow_pickle=False)
    params = []
    for i, ent in enumerate(manifest["params"]):
        base = None
        if ent["encoding"] == "topk_rows":
            if base_params is None:
                raise ValueError(
                    "decode_update: record carries topk_rows entries; "
                    "pass base_params")
            base = base_params[i]
        params.append(_decode_param(ent, payload, f"p{i}", base=base))
    aux = [_decode_param(ent, payload, f"a{i}")
           for i, ent in enumerate(manifest.get("aux", []))]
    return params, aux


# --------------------------------------------------------------- the bus ---

class ModelBus:
    """One shared bus directory: version-stamped update records plus
    their quarantine (reject) files.

    Layout (``v<NNNNNNNN>`` is the zero-padded version)::

        v00000003.update             npz payload (atomic write)
        v00000003.json               manifest, written AFTER the payload
        reject-v00000003-<who>.json  a subscriber's quarantine record

    Multi-writer is not a bus concern: the trainer's writer rank is the
    single publisher (subscribers only write reject files, which are
    per-worker named).
    """

    def __init__(self, directory, compress_threshold=None, keep=8):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.compress_threshold = (DEFAULT_COMPRESS_THRESHOLD
                                   if compress_threshold is None
                                   else int(compress_threshold))
        self.keep = int(keep) if keep else 0
        self.torn_skips = 0
        # publisher-side memory of the last published (decoded) values —
        # the base the NEXT topk_rows record diffs against
        self._last_vals = {}
        self._last_version = None
        self._rolled_back = set()   # quarantined versions already rolled back

    # ------------------------------------------------------------- paths --
    def _vname(self, version):
        return f"v{int(version):08d}"

    def payload_path(self, version):
        return os.path.join(self.directory,
                            self._vname(version) + PAYLOAD_SUFFIX)

    def manifest_path(self, version):
        return os.path.join(self.directory,
                            self._vname(version) + MANIFEST_SUFFIX)

    def reject_path(self, version, worker):
        worker = "".join(c if c.isalnum() or c in "-_" else "_"
                         for c in str(worker)) or "anon"
        return os.path.join(
            self.directory, f"reject-{self._vname(version)}-{worker}.json")

    # ----------------------------------------------------------- listing --
    def _torn(self, path, err):
        self.torn_skips += 1
        _bump("torn_skips")
        _flight.rec("modelbus.torn_skip", os.path.basename(path))
        if self.directory not in _torn_warned:
            _torn_warned.add(self.directory)
            _logger.warning(
                "model bus %s: skipping torn/partial record %s (%s); "
                "further torn records on this bus are counted "
                "(mxtpu_modelbus_torn_skips_total) but not logged again",
                self.directory, os.path.basename(path), err)

    def manifests(self):
        """Readable manifests, ascending by version. Torn/partial
        manifest files are skipped through the warn-once latch."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("v") and name.endswith(MANIFEST_SUFFIX)):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    m = json.load(f)
                if not isinstance(m.get("version"), int) \
                        or not isinstance(m.get("params"), list):
                    raise ValueError("manifest missing version/params")
            except (OSError, ValueError) as e:
                self._torn(path, e)
                continue
            out.append(m)
        out.sort(key=lambda m: m["version"])
        return out

    def latest(self):
        """The newest readable manifest, or None."""
        mans = self.manifests()
        return mans[-1] if mans else None

    def versions(self):
        """Every version with a record on disk (manifest or payload),
        readable or not — the allocator's collision floor."""
        vs = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            stem = name
            for suf in (PAYLOAD_SUFFIX, MANIFEST_SUFFIX):
                if stem.endswith(suf):
                    stem = stem[: -len(suf)]
                    break
            if stem.startswith("reject-"):
                stem = stem[len("reject-"):].split("-")[0]
            if stem.startswith("v") and stem[1:].isdigit():
                vs.add(int(stem[1:]))
        return sorted(vs)

    def next_version(self):
        vs = self.versions()
        return (vs[-1] + 1) if vs else 1

    def quarantined(self):
        """Versions any subscriber has rejected (a reject file exists)."""
        out = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if name.startswith("reject-v") and name.endswith(".json"):
                tok = name[len("reject-v"):].split("-")[0].split(".")[0]
                if tok.isdigit():
                    out.add(int(tok))
        return out

    def rejects(self):
        """Every readable reject record, ascending by version — what the
        publisher/supervisor (and diagnose) act on."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("reject-v") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        out.sort(key=lambda r: r.get("version", 0))
        return out

    def write_reject(self, version, reason, worker="", detail=""):
        """Quarantine `version`: an atomic, per-worker reject record."""
        rec = {"version": int(version), "reason": str(reason),
               "detail": str(detail), "worker": str(worker),
               "time": time.time()}
        payload = json.dumps(rec, indent=1, sort_keys=True)

        def writer(tmp):
            with open(tmp, "w") as f:
                f.write(payload)

        _checkpoint.atomic_write(self.reject_path(version, worker), writer)
        return rec

    # ---------------------------------------------------------- publish --
    def publish(self, params, step, aux=(), meta=None, model=None,
                encodings=None, topk=None, version=None):
        """Write one update record; returns its version, or None when
        the finite gate refused it.

        params / aux : iterables of ``(name, array)`` in serving order.
        encodings : optional {name: "full"|"int8_rows"|"topk_rows"}
            overriding the size-based default.
        topk : optional {name: k} — publish only the k most-changed rows
            vs the previous publish (falls back to full/int8 when there
            is no previous publish to diff against).
        """
        named = [(str(n), _np.asarray(a)) for n, a in params]
        aux_named = [(str(n), _np.asarray(a)) for n, a in aux]

        # the finite gate: a NaN/Inf update is NEVER published — the
        # nan-guard's job upstream, re-checked here so a bus can't carry
        # divergence into a fleet even when the guard is off
        for n, a in named + aux_named:
            if not _is_finite(a):
                _bump("publish_skipped_nonfinite")
                _flight.rec("modelbus.skip_nonfinite", n,
                            f"step={int(step)}")
                _logger.warning(
                    "model bus %s: NOT publishing step %d — parameter "
                    "%r is non-finite", self.directory, int(step), n)
                return None

        # injection AFTER the gate = in-transit poison: the subscriber's
        # validation, not the publisher's gate, must catch it (nan mode
        # poisons the record's first parameter)
        if named:
            n0, a0 = named[0]
            named[0] = (n0, _np.asarray(
                _faults.point("modelbus.publish", a0)))
        else:
            _faults.point("modelbus.publish")

        if version is None:
            version = self.next_version()
        version = int(version)
        base_version = None
        out, census_p, census_a = {}, [], []
        decoded_vals = {}
        for i, (n, a) in enumerate(named):
            enc = (encodings or {}).get(n)
            base = self._last_vals.get(n) if topk and n in (topk or {}) \
                else None
            if enc is None:
                if topk and n in topk and base is not None \
                        and base.shape == a.shape:
                    enc = "topk_rows"
                elif (a.size >= self.compress_threshold and a.ndim >= 2
                        and a.dtype.kind == "f"):
                    enc = "int8_rows"
                else:
                    enc = "full"
            if enc == "topk_rows" and (base is None
                                       or base.shape != a.shape):
                enc = "full"   # nothing to diff against yet
            ent = {"name": n, "shape": list(a.shape),
                   "dtype": str(a.dtype), "encoding": enc}
            ent.update(_encode_param(a, enc, f"p{i}", out, base=base,
                                     k=(topk or {}).get(n)))
            if enc == "topk_rows":
                base_version = self._last_version
            census_p.append(ent)
        for i, (n, a) in enumerate(aux_named):
            census_a.append({"name": n, "shape": list(a.shape),
                             "dtype": str(a.dtype), "encoding": "full"})
            out[f"a{i}"] = a

        def writer(tmp):
            with open(tmp, "wb") as f:
                _np.savez(f, **out)

        crc, size = _checkpoint.atomic_write(
            self.payload_path(version), writer)
        manifest = {"version": version, "step": int(step),
                    "time": time.time(),
                    "file": os.path.basename(self.payload_path(version)),
                    "crc32": int(crc), "size": int(size),
                    "params": census_p, "aux": census_a,
                    "base_version": base_version,
                    "model": model, "meta": dict(meta or {}),
                    "publisher": {"pid": os.getpid()}}
        mpayload = json.dumps(manifest, indent=1, sort_keys=True)

        def mwriter(tmp):
            with open(tmp, "w") as f:
                f.write(mpayload)

        _checkpoint.atomic_write(self.manifest_path(version), mwriter)
        _bump("published")
        _flight.rec("modelbus.publish", str(version), f"step={int(step)}")

        # remember the decoded (as-a-subscriber-sees-them) values so the
        # next topk publish diffs against what subscribers actually hold
        for i, (n, _a) in enumerate(named):
            decoded_vals[n] = _decode_param(
                census_p[i], out, f"p{i}", base=self._last_vals.get(n))
        self._last_vals.update(decoded_vals)
        self._last_version = version
        self._rotate()
        return version

    def _rotate(self):
        if not self.keep:
            return
        mans = self.manifests()
        for m in mans[:-self.keep] if len(mans) > self.keep else []:
            for path in (self.payload_path(m["version"]),
                         self.manifest_path(m["version"])):
                try:
                    os.remove(path)
                except OSError:
                    pass

    # --------------------------------------------------- read / rollback --
    def read(self, version, verify=True):
        """``(manifest, payload bytes)`` for one version; `verify`
        checks size+CRC against the manifest (ValueError on mismatch)."""
        with open(self.manifest_path(version)) as f:
            # manifests are atomic_write-published and immutable per
            # version; a vanished (rotated) file raises OSError to the
            # caller by contract, never a torn parse
            manifest = json.load(f)  # concur: torn-ok
        with open(self.payload_path(version), "rb") as f:
            blob = f.read()
        if verify and (len(blob) != manifest["size"] or
                       (zlib.crc32(blob) & 0xFFFFFFFF)
                       != manifest["crc32"]):
            raise ValueError(
                f"bus record v{version} payload fails CRC/size "
                "verification")
        return manifest, blob

    def auto_rollback(self, worker=""):
        """Rollback = re-publish: when the newest version on the bus is
        quarantined, re-publish the newest GOOD (non-quarantined,
        self-contained) version as a fresh higher version so every
        subscriber converges back onto known-good weights. Returns the
        new version, or None when no rollback was needed/possible.
        Idempotent: each quarantined head triggers at most one
        re-publication per bus handle."""
        mans = self.manifests()
        if not mans:
            return None
        q = self.quarantined()
        head = mans[-1]
        if head["version"] not in q \
                or head["version"] in self._rolled_back:
            return None
        good = [m for m in mans
                if m["version"] not in q
                and m.get("base_version") is None]
        if not good:
            self._rolled_back.add(head["version"])
            _logger.warning(
                "model bus %s: head version %d is quarantined but no "
                "good version remains to roll back to",
                self.directory, head["version"])
            return None
        src = good[-1]
        try:
            manifest, blob = self.read(src["version"])
            params, aux = decode_update(manifest, blob)
        except (OSError, ValueError) as e:
            self._torn(self.payload_path(src["version"]), e)
            return None
        names_p = [e["name"] for e in manifest["params"]]
        names_a = [e["name"] for e in manifest.get("aux", [])]
        new_version = self.publish(
            list(zip(names_p, params)), step=manifest["step"],
            aux=list(zip(names_a, aux)), model=manifest.get("model"),
            encodings={n: "full" for n in names_p},
            meta={"rollback_of": head["version"],
                  "source_version": src["version"]})
        if new_version is None:
            return None
        self._rolled_back.add(head["version"])
        _bump("rollbacks")
        _flight.rec("modelbus.rollback", str(new_version),
                    f"of=v{head['version']} from=v{src['version']}")
        _logger.warning(
            "model bus %s: version %d quarantined (%s); rolled back by "
            "re-publishing good version %d as version %d",
            self.directory, head["version"],
            ", ".join(sorted({r["reason"] for r in self.rejects()
                              if r.get("version") == head["version"]}))
            or "?", src["version"], new_version)
        return new_version

    def describe(self):
        """JSON-able bus summary (diagnose's Model Bus report)."""
        mans = self.manifests()
        q = self.quarantined()
        return {"directory": self.directory,
                "versions": [m["version"] for m in mans],
                "latest": mans[-1]["version"] if mans else None,
                "latest_step": mans[-1]["step"] if mans else None,
                "quarantined": sorted(q),
                "rejects": self.rejects(),
                "torn_skips": self.torn_skips,
                "keep": self.keep}

    def __repr__(self):
        return f"ModelBus({self.directory!r})"


# ----------------------------------------------------------- the watcher ---

class BusWatcher:
    """The subscriber half: poll a bus from a serving process, validate
    each new version (CRC → census → finiteness), and flip every census-
    matching :class:`~mxnet_tpu.serving.model.ServedModel` of the bound
    :class:`~mxnet_tpu.serving.server.ModelServer` between batches.

    Validation failures quarantine the version on the bus and keep the
    last good version pinned; the watcher never applies a version twice
    and never moves backwards.
    """

    def __init__(self, server, bus, poll=0.25, worker=None):
        self._server = server
        self.bus = bus if isinstance(bus, ModelBus) else ModelBus(bus)
        self.poll = float(poll)
        self.worker = str(worker or f"pid{os.getpid()}")
        self.applied_version = 0
        self.applied_step = None
        self.applied_total = 0
        self.applied_models = []
        self.latest_version = 0
        self.latest_step = None
        self.rejected = {}          # version -> reason (this watcher's)
        self._stop_evt = threading.Event()
        self._thread = None
        _WATCHERS.add(self)

    # --------------------------------------------------------- lifecycle --
    def start(self):
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"mxtpu-modelbus-{self.worker}")
            self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self):
        while not self._stop_evt.is_set():
            try:
                self.poll_once()
            except Exception as e:   # the watcher must never die silently
                _logger.warning("model bus watcher %s: poll failed: %s: "
                                "%s", self.worker, type(e).__name__, e)
            self._stop_evt.wait(self.poll)

    # ------------------------------------------------------------- state --
    def age_steps(self):
        """Bounded-staleness distance: latest published step minus the
        applied step (0 when fully caught up or the bus is empty)."""
        if self.latest_step is None:
            return 0
        return max(0, int(self.latest_step) - int(self.applied_step or 0))

    def stats(self):
        return {"bus_dir": self.bus.directory,
                "worker": self.worker,
                "applied_version": self.applied_version,
                "applied_step": self.applied_step,
                "applied_total": self.applied_total,
                "applied_models": list(self.applied_models),
                "latest_version": self.latest_version,
                "latest_step": self.latest_step,
                "age_steps": self.age_steps(),
                "rejected": dict(self.rejected),
                "torn_skips": self.bus.torn_skips}

    def model_names(self):
        try:
            return [m.name for m in self._server.container]
        except Exception:
            return []

    # ------------------------------------------------------------- apply --
    def poll_once(self):
        """One poll: apply the newest applicable version. Returns the
        version applied, or None."""
        mans = self.bus.manifests()
        if not mans:
            return None
        self.latest_version = mans[-1]["version"]
        self.latest_step = mans[-1].get("step")
        q = self.bus.quarantined()
        cands = [m for m in mans
                 if m["version"] > self.applied_version
                 and m["version"] not in q
                 and m["version"] not in self.rejected]
        for m in reversed(cands):    # newest applicable wins
            try:
                if self._apply(m):
                    return m["version"]
            except _StaleRecord:
                _bump("stale_skips")
                continue
            except Exception as e:
                self._reject(m, "apply_error",
                             f"{type(e).__name__}: {e}")
                continue
        return None

    def _reject(self, manifest, reason, detail=""):
        version = manifest["version"]
        self.rejected[version] = reason
        try:
            self.bus.write_reject(version, reason, worker=self.worker,
                                  detail=detail)
        except OSError as e:
            _logger.warning("model bus watcher %s: could not write "
                            "reject record for v%d: %s", self.worker,
                            version, e)
        _bump("rejected")
        _flight.rec("modelbus.reject", str(version), reason)
        _logger.warning(
            "model bus watcher %s: REJECTED version %d (%s%s) — "
            "quarantined; serving stays pinned at version %d",
            self.worker, version, reason,
            f": {detail}" if detail else "", self.applied_version)
        return False

    def _match(self, model, manifest):
        """Map manifest param positions onto `model`'s params: by name
        when both sides carry a matching name set, positionally when the
        counts + shapes + dtypes line up (gluon auto-prefixes differ
        across processes). Returns ``(p_order, a_order)`` — for model
        position j, take manifest entry ``order[j]`` — or None."""
        praws, araws, _v = model.pinned()
        ents_p, ents_a = manifest["params"], manifest.get("aux", [])
        if len(ents_p) != len(praws) or len(ents_a) != len(araws):
            return None

        def order_for(ents, raws, names):
            if names and all(e.get("name") for e in ents) \
                    and set(names) == {e["name"] for e in ents} \
                    and len(set(names)) == len(names):
                by_name = {e["name"]: i for i, e in enumerate(ents)}
                order = [by_name[n] for n in names]
            else:
                order = list(range(len(ents)))
            for j, raw in enumerate(raws):
                e = ents[order[j]]
                if tuple(e["shape"]) != tuple(raw.shape) \
                        or str(e["dtype"]) != str(raw.dtype):
                    return None
            return order

        p_order = order_for(ents_p, praws,
                            getattr(model, "param_names", None))
        if p_order is None:
            return None
        a_order = order_for(ents_a, araws,
                            getattr(model, "aux_names", None))
        if a_order is None:
            return None
        return p_order, a_order

    def _apply(self, m):
        version = m["version"]
        try:
            with open(self.bus.payload_path(version), "rb") as f:
                blob = f.read()
        except OSError:
            # payload gone mid-read (rotation) or not yet visible —
            # never happens for a manifest written after it on one
            # filesystem, but a remounted/synced bus can race
            raise _StaleRecord
        # 'modelbus.apply' injection on the raw bytes: corrupt mode
        # flips bits the CRC check below must catch; delay/hang stall
        # the apply path; raise surfaces as an apply_error reject
        blob = _faults.point("modelbus.apply", blob)
        if not isinstance(blob, (bytes, bytearray)) \
                or len(blob) != m["size"] \
                or (zlib.crc32(bytes(blob)) & 0xFFFFFFFF) != m["crc32"]:
            return self._reject(
                m, "crc_mismatch",
                f"payload size/CRC does not match manifest "
                f"(size {len(blob) if blob is not None else 0} vs "
                f"{m['size']})")

        container = getattr(self._server, "container", self._server)
        targets = []
        for model in container:
            orders = self._match(model, m)
            if orders is not None:
                targets.append((model, orders))
        if not targets:
            return self._reject(
                m, "census_mismatch",
                f"no served model matches the record census "
                f"({len(m['params'])} params) — served: "
                f"{[mm.name for mm in container]}")

        if m.get("base_version") is not None \
                and int(m["base_version"]) != int(self.applied_version):
            # sparse rows diff against a base this worker does not hold;
            # wait for a self-contained record instead of quarantining
            raise _StaleRecord

        npz = _np.load(io.BytesIO(bytes(blob)), allow_pickle=False)
        applied_names = []
        swaps = []
        for model, (p_order, a_order) in targets:
            base = None
            if m.get("base_version") is not None:
                import jax

                praws, _a, _v = model.pinned()
                base = [None] * len(m["params"])
                for j, src in enumerate(p_order):
                    base[src] = _np.asarray(jax.device_get(praws[j]))
            params, aux = decode_update(m, npz, base_params=base)
            for ent, arr in zip(m["params"] + m.get("aux", []),
                                params + aux):
                if not _is_finite(arr):
                    return self._reject(
                        m, "nonfinite",
                        f"decoded parameter {ent.get('name')!r} "
                        "contains NaN/Inf")
            swaps.append((model,
                          [params[src] for src in p_order],
                          [aux[src] for src in a_order]))
        # validation done for EVERY target — now flip them all; each
        # model's flip is one atomic pinned-tuple rebind, so a batch
        # sees exactly one consistent (params, version) pair
        for model, praws, araws in swaps:
            model.swap_params(praws, version, aux_raws=araws)
            applied_names.append(model.name)
        self.applied_version = version
        self.applied_step = m.get("step")
        self.applied_models = applied_names
        self.applied_total += 1
        _bump("applied")
        _flight.rec("modelbus.apply", str(version),
                    f"step={m.get('step')} models={len(applied_names)}")
        _logger.info("model bus watcher %s: applied version %d "
                     "(step %s) to %s", self.worker, version,
                     m.get("step"), applied_names)
        return True
