"""Quantization ops (parity: `src/operator/quantization/`).

Symmetric int8 quantization with int32 accumulation — the MXU runs int8
matmuls at twice the bf16 rate, so `_contrib_quantized_*` ops lower to
`lax.dot_general`/`conv_general_dilated` with int8 operands and
``preferred_element_type=int32`` (the TPU analogue of the reference's
cuDNN/MKLDNN int8 paths, `quantized_conv.cu`, `quantized_fully_connected.cc`).

Scale convention (matches the reference's symmetric int8 'auto' path,
`quantize_v2-inl.h`): scale = max(|min_range|, |max_range|) / 127; zero
point is always 0.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _scale(min_range, max_range):
    s = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 127.0
    # all-zero range (dead activation): scale 1 maps everything to q=0
    return jnp.where(s > 0, s, 1.0)


def _quantize(data, scale):
    q = jnp.clip(jnp.round(data / scale), -127, 127)
    return q.astype(jnp.int8)


@register("_contrib_quantize", num_outputs=3)
def _contrib_quantize(data, min_range, max_range, out_type="int8"):
    """parity: quantize.cc — float -> int8 with provided ranges."""
    s = _scale(min_range, max_range)
    return _quantize(data, s), min_range.astype(jnp.float32), \
        max_range.astype(jnp.float32)


@register("_contrib_quantize_v2", num_outputs=3)
def _contrib_quantize_v2(data, min_calib_range=None, max_calib_range=None,
                         out_type="int8"):
    """parity: quantize_v2.cc — calibrated ranges as attrs, or dynamic
    (min/max of the batch) when not provided."""
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(data).astype(jnp.float32)
        max_r = jnp.max(data).astype(jnp.float32)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    s = _scale(min_r, max_r)
    return _quantize(data, s), min_r, max_r


@register("_contrib_dequantize")
def _contrib_dequantize(data, min_range, max_range, out_type="float32"):
    """parity: dequantize.cc."""
    s = _scale(min_range, max_range)
    return data.astype(jnp.float32) * s


@register("_contrib_requantize", num_outputs=3)
def _contrib_requantize(data, min_range, max_range, min_calib_range=None,
                        max_calib_range=None):
    """parity: requantize.cc — int32 accumulator -> int8 with new range."""
    in_scale = jnp.maximum(jnp.abs(min_range),
                           jnp.abs(max_range)) / (2.0 ** 31 - 1)
    f = data.astype(jnp.float32) * in_scale
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(f).astype(jnp.float32)
        max_r = jnp.max(f).astype(jnp.float32)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    s = _scale(min_r, max_r)
    return _quantize(f, s), min_r, max_r


@register("_contrib_quantized_fully_connected")
def _quantized_fully_connected(data, weight, scale, bias=None, num_hidden=1,
                               no_bias=False, flatten=True,
                               min_calib_range=0.0, max_calib_range=0.0):
    """int8 FullyConnected: activation quantized with the calibrated range,
    int8 x int8 -> int32 on the MXU, per-output-channel dequantize.

    weight: int8 (num_hidden, K); scale: float32 (num_hidden,) per-channel
    weight scales. parity: quantized_fully_connected.cc.
    """
    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    s_x = _scale(jnp.float32(min_calib_range), jnp.float32(max_calib_range))
    qx = _quantize(data, s_x)
    acc = jax.lax.dot_general(
        qx, weight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (s_x * scale)
    if bias is not None and not no_bias:
        out = out + bias
    return out


@register("_contrib_quantized_conv")
def _quantized_conv(data, weight, scale, bias=None, kernel=(), stride=(),
                    dilate=(), pad=(), num_filter=1, num_group=1,
                    no_bias=False, layout=None, min_calib_range=0.0,
                    max_calib_range=0.0):
    """int8 Convolution (NCHW): parity: quantized_conv.cc.

    weight: int8 (num_filter, C/g, *kernel); scale: float32 (num_filter,)."""
    n = len(kernel)
    stride = tuple(stride) if stride else (1,) * n
    dilate = tuple(dilate) if dilate else (1,) * n
    pad = tuple(pad) if pad else (0,) * n
    s_x = _scale(jnp.float32(min_calib_range), jnp.float32(max_calib_range))
    qx = _quantize(data, s_x)
    spatial = "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    acc = jax.lax.conv_general_dilated(
        qx, weight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * \
        (s_x * scale).reshape((1, -1) + (1,) * n)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out
