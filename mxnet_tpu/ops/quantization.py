"""Quantization ops (parity: `src/operator/quantization/`).

Symmetric int8 quantization with int32 accumulation — the MXU runs int8
matmuls at twice the bf16 rate, so `_contrib_quantized_*` ops lower to
`lax.dot_general`/`conv_general_dilated` with int8 operands and
``preferred_element_type=int32`` (the TPU analogue of the reference's
cuDNN/MKLDNN int8 paths, `quantized_conv.cu`, `quantized_fully_connected.cc`).

Scale convention (matches the reference's symmetric int8 'auto' path,
`quantize_v2-inl.h`): scale = max(|min_range|, |max_range|) / 127; zero
point is always 0.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _scale(min_range, max_range):
    s = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 127.0
    # all-zero range (dead activation): scale 1 maps everything to q=0
    return jnp.where(s > 0, s, 1.0)


def _quantize(data, scale):
    q = jnp.clip(jnp.round(data / scale), -127, 127)
    return q.astype(jnp.int8)


@register("_contrib_quantize", num_outputs=3)
def _contrib_quantize(data, min_range, max_range, out_type="int8"):
    """parity: quantize.cc — float -> int8 with provided ranges."""
    s = _scale(min_range, max_range)
    return _quantize(data, s), min_range.astype(jnp.float32), \
        max_range.astype(jnp.float32)


@register("_contrib_quantize_v2", num_outputs=3)
def _contrib_quantize_v2(data, min_calib_range=None, max_calib_range=None,
                         out_type="int8"):
    """parity: quantize_v2.cc — calibrated ranges as attrs, or dynamic
    (min/max of the batch) when not provided."""
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(data).astype(jnp.float32)
        max_r = jnp.max(data).astype(jnp.float32)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    s = _scale(min_r, max_r)
    return _quantize(data, s), min_r, max_r


@register("_contrib_dequantize")
def _contrib_dequantize(data, min_range, max_range, out_type="float32"):
    """parity: dequantize.cc."""
    s = _scale(min_range, max_range)
    return data.astype(jnp.float32) * s


@register("_contrib_requantize", num_outputs=3)
def _contrib_requantize(data, min_range, max_range, min_calib_range=None,
                        max_calib_range=None):
    """parity: requantize.cc — int32 accumulator -> int8 with new range."""
    in_scale = jnp.maximum(jnp.abs(min_range),
                           jnp.abs(max_range)) / (2.0 ** 31 - 1)
    f = data.astype(jnp.float32) * in_scale
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(f).astype(jnp.float32)
        max_r = jnp.max(f).astype(jnp.float32)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    s = _scale(min_r, max_r)
    return _quantize(f, s), min_r, max_r


@register("_contrib_quantized_fully_connected")
def _quantized_fully_connected(data, weight, scale, bias=None, num_hidden=1,
                               no_bias=False, flatten=True,
                               min_calib_range=0.0, max_calib_range=0.0,
                               min_out_calib_range=None,
                               max_out_calib_range=None):
    """int8 FullyConnected: activation quantized with the calibrated range,
    int8 x int8 -> int32 on the MXU, per-output-channel dequantize.

    weight: int8 (num_hidden, K); scale: float32 per-channel weight
    scales (num_hidden,), or a single-element/scalar tensor for
    tensor-wise granularity. ``min_out_calib_range``/
    ``max_out_calib_range`` carry the observed OUTPUT range (stamped by
    the graph pass) for the ONNX exporter's y_scale and requantize
    fusion — they do not change the computation here.
    parity: quantized_fully_connected.cc.
    """
    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    s_x = _scale(jnp.float32(min_calib_range), jnp.float32(max_calib_range))
    qx = _quantize(data, s_x)
    if qx.ndim == 2:
        # MXU-tiled Pallas GEMM with the dequant+bias epilogue fused in
        # VMEM (registry family int8_gemm) where the dispatch table
        # proved it; the XLA baseline is this op's original
        # dot_general+epilogue, so routing is bit-exact either way
        from .. import kernels as _kernels

        return _kernels.dispatch(
            "int8_gemm", qx, weight, s_x * scale,
            bias=None if (bias is None or no_bias) else bias)
    acc = jax.lax.dot_general(
        qx, weight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (s_x * scale)
    if bias is not None and not no_bias:
        out = out + bias
    return out


@register("_contrib_quantized_conv")
def _quantized_conv(data, weight, scale, bias=None, kernel=(), stride=(),
                    dilate=(), pad=(), num_filter=1, num_group=1,
                    no_bias=False, layout=None, min_calib_range=0.0,
                    max_calib_range=0.0, min_out_calib_range=None,
                    max_out_calib_range=None):
    """int8 Convolution (NCHW): parity: quantized_conv.cc.

    weight: int8 (num_filter, C/g, *kernel); scale: float32
    (num_filter,) per-channel, or single-element for tensor-wise
    granularity. ``min_out_calib_range``/``max_out_calib_range`` carry
    the observed output range for the ONNX exporter (no effect on the
    computation)."""
    n = len(kernel)
    stride = tuple(stride) if stride else (1,) * n
    dilate = tuple(dilate) if dilate else (1,) * n
    pad = tuple(pad) if pad else (0,) * n
    s_x = _scale(jnp.float32(min_calib_range), jnp.float32(max_calib_range))
    qx = _quantize(data, s_x)
    spatial = "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    acc = jax.lax.conv_general_dilated(
        qx, weight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * \
        (s_x * scale).reshape((1, -1) + (1,) * n)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# ---------------------------------------------- quantized op tail ----------
# parity: quantized_activation.cc, quantized_concat.cc,
# quantized_elemwise_add/mul.cc, quantized_flatten.cc,
# quantized_pooling.cc, quantized_batch_norm.cc, quantized_embedding
# (quantized_indexing_op.cc), quantize_asym. Contract everywhere:
# (int8 data, min_range, max_range) in, (int8 out, min, max) out.

@register("_contrib_quantized_act", num_outputs=3)
def _quantized_act(data, min_data, max_data, act_type="relu"):
    """int8 Activation (parity: quantized_activation.cc): relu clips the
    range to (0, max) and requantizes the payload onto the new scale;
    other act types pass through unchanged."""
    if act_type != "relu":
        return data, min_data, max_data
    # the clipped range (0, max) has a new scale — requantize the payload,
    # not just the range metadata
    s_in = _scale(min_data, max_data)
    min_out = jnp.maximum(min_data, 0.0)
    s_out = _scale(min_out, max_data)
    q = jnp.maximum(data, 0).astype(jnp.float32) * (s_in / s_out)
    return jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8), \
        min_out, max_data


@register("_contrib_quantized_flatten", num_outputs=3)
def _quantized_flatten(data, min_data, max_data):
    """int8 Flatten (parity: quantized_flatten.cc): pure reshape — the
    payload and its range metadata pass through untouched."""
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("_contrib_quantized_concat", num_outputs=3)
def _quantized_concat(*args, dim=1, num_args=None):
    """args = [d0, d1, ..., min0, max0, min1, max1, ...] (reference input
    layout: all data first, then min/max pairs). Requantizes every input
    to the widest range before concatenating."""
    n = len(args) // 3
    datas, mins, maxs = args[:n], args[n::2][:n], args[n + 1::2][:n]
    min_out = mins[0]
    max_out = maxs[0]
    for m in mins[1:]:
        min_out = jnp.minimum(min_out, m)
    for m in maxs[1:]:
        max_out = jnp.maximum(max_out, m)
    s_out = _scale(min_out, max_out)
    parts = []
    for d, mn, mx in zip(datas, mins, maxs):
        s_in = _scale(mn, mx)
        parts.append(_quantize(d.astype(jnp.float32) * s_in, s_out))
    return jnp.concatenate(parts, axis=dim), min_out, max_out


@register("_contrib_quantized_elemwise_add", num_outputs=3)
def _quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """int8 elementwise add (parity: quantized_elemwise_add.cc): both
    sides dequantize onto fp32, the sum requantizes onto its own
    dynamic range; returns (int8 out, min, max)."""
    sl = _scale(lhs_min, lhs_max)
    sr = _scale(rhs_min, rhs_max)
    out = lhs.astype(jnp.float32) * sl + rhs.astype(jnp.float32) * sr
    min_out = jnp.min(out).astype(jnp.float32)
    max_out = jnp.max(out).astype(jnp.float32)
    s = _scale(min_out, max_out)
    return _quantize(out, s), min_out, max_out


@register("_contrib_quantized_elemwise_mul", num_outputs=3)
def _quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """int8 elementwise multiply (parity: quantized_elemwise_mul.cc):
    dequantize both sides, multiply in fp32, requantize onto the
    product's dynamic range; returns (int8 out, min, max)."""
    sl = _scale(lhs_min, lhs_max)
    sr = _scale(rhs_min, rhs_max)
    out = (lhs.astype(jnp.float32) * sl) * (rhs.astype(jnp.float32) * sr)
    min_out = jnp.min(out).astype(jnp.float32)
    max_out = jnp.max(out).astype(jnp.float32)
    s = _scale(min_out, max_out)
    return _quantize(out, s), min_out, max_out


@register("_contrib_quantized_pooling", num_outputs=3)
def _quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                       pool_type="max", stride=(1, 1), pad=(0, 0),
                       global_pool=False, pooling_convention="valid"):
    """int8 pooling: max-pool stays in int8 (order-preserving); avg-pool
    accumulates in int32 like the reference."""
    from .nn import _pooling

    if pool_type == "max":
        out = _pooling.fn(data.astype(jnp.float32), kernel=kernel,
                          pool_type="max", stride=stride, pad=pad,
                          global_pool=global_pool,
                          pooling_convention=pooling_convention)
        return out.astype(jnp.int8), min_data, max_data
    out = _pooling.fn(data.astype(jnp.float32), kernel=kernel,
                      pool_type=pool_type, stride=stride, pad=pad,
                      global_pool=global_pool,
                      pooling_convention=pooling_convention)
    return jnp.clip(jnp.round(out), -127, 127).astype(jnp.int8), \
        min_data, max_data


@register("_contrib_quantized_batch_norm", num_outputs=3)
def _quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                          min_data, max_data, eps=1e-3, min_calib_range=None,
                          max_calib_range=None, **kw):
    """int8 inference BatchNorm (parity: quantized_batch_norm.cc):
    dequantize, normalize with the moving statistics in fp32, requantize
    onto the calibrated range (or the batch's own range when
    uncalibrated)."""
    s_in = _scale(min_data, max_data)
    x = data.astype(jnp.float32) * s_in
    shape = [1, -1] + [1] * (data.ndim - 2)
    inv = gamma / jnp.sqrt(moving_var + eps)
    out = (x - moving_mean.reshape(shape)) * inv.reshape(shape) + \
        beta.reshape(shape)
    if min_calib_range is not None:
        min_o = jnp.float32(min_calib_range)
        max_o = jnp.float32(max_calib_range)
    else:
        min_o = jnp.min(out).astype(jnp.float32)
        max_o = jnp.max(out).astype(jnp.float32)
    return _quantize(out, _scale(min_o, max_o)), min_o, max_o


@register("_contrib_quantized_embedding", num_outputs=3)
def _quantized_embedding(data, weight, min_weight, max_weight,
                         input_dim=None, output_dim=None):
    """int8 Embedding lookup (parity: quantized_indexing_op.cc): the
    gather stays in int8 — 4x less table traffic than fp32, the actual
    speed win for bandwidth-bound embedding models — and the range
    metadata passes through so a downstream dequantize (cast * scale,
    fused into the gather's consumer by XLA) restores fp32."""
    out = jnp.take(weight, data.astype(jnp.int32), axis=0)
    # XLA CPU otherwise fuses this gather into a consuming reduction and
    # re-materializes it element-by-element, losing the vectorized int8
    # row copy (the entire point of the op); the barrier pins the gather
    # as one materialized memcpy-shaped kernel. Semantically identity.
    out = jax.lax.optimization_barrier(out)
    return out, min_weight, max_weight


@register("_contrib_quantize_asym", num_outputs=3)
def _quantize_asym(data, min_calib_range=None, max_calib_range=None):
    """parity: quantize_asym-inl.h — affine uint8-style quantization
    (scale + shift), returned as (int8 out, scale, shift)."""
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(data).astype(jnp.float32)
        max_r = jnp.max(data).astype(jnp.float32)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    rng = jnp.where(max_r > min_r, max_r - min_r, 1.0)
    scale = 255.0 / rng
    shift = -min_r * scale - 128.0
    q = jnp.clip(jnp.round(data * scale + shift), -128, 127)
    return q.astype(jnp.int8), scale, shift


@register("_contrib_calibrate_entropy", num_outputs=2)
def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """parity: calibrate.cc — KL-divergence threshold selection over a
    collected histogram; returns (min, max) calibration thresholds."""
    # Symmetric search: evaluate thresholds at every bin boundary from the
    # center out, pick the one minimizing KL(P || quantized P).
    n_bins = hist.shape[0]
    hist_f = hist.astype(jnp.float32)
    centers = (hist_edges[:-1] + hist_edges[1:]) / 2.0
    abs_max = jnp.maximum(jnp.abs(hist_edges[0]), jnp.abs(hist_edges[-1]))

    def kl_for(threshold):
        inside = jnp.abs(centers) <= threshold
        p = jnp.where(inside, hist_f, 0.0)
        outliers = jnp.sum(hist_f) - jnp.sum(p)
        p = p + jnp.where(inside, outliers / jnp.maximum(
            jnp.sum(inside), 1), 0.0)
        # quantize into num_quantized_bins buckets then expand back
        bucket = jnp.clip(((jnp.abs(centers) / jnp.maximum(threshold, 1e-12))
                           * (num_quantized_bins - 1)).astype(jnp.int32),
                          0, num_quantized_bins - 1)
        q_sum = jax.ops.segment_sum(p, bucket, num_quantized_bins)
        q_cnt = jax.ops.segment_sum(jnp.where(inside, 1.0, 0.0), bucket,
                                    num_quantized_bins)
        q = jnp.where(q_cnt > 0, q_sum / jnp.maximum(q_cnt, 1.0), 0.0)[bucket]
        q = jnp.where(inside, q, 0.0)
        p_n = p / jnp.maximum(jnp.sum(p), 1e-12)
        q_n = q / jnp.maximum(jnp.sum(q), 1e-12)
        return jnp.sum(jnp.where((p_n > 0) & (q_n > 0),
                                 p_n * jnp.log(p_n / q_n), 0.0))

    n_cand = 64
    cands = jnp.linspace(abs_max / n_cand, abs_max, n_cand)
    kls = jax.vmap(kl_for)(cands)
    best = cands[jnp.argmin(kls)]
    return -best, best
