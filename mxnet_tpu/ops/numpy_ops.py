"""NumPy-frontend ops: `_npi_*` registrations.

Parity target: `src/operator/numpy/` (~33.5k LoC, 147 `_npi_*`
registrations: np_elemwise_broadcast_op.cc, np_matrix_op.cc,
np_einsum_op.cc, np_tensordot_op.cc, linalg/*, random/*). Each op here is
the jnp emitter for one `_npi_` name; `mx.np` functions dispatch through
the registry so the tape, AMP pass, profiler and opperf all see them like
any other op.

Unlike the legacy op set (MXNet 1.x semantics), these follow NumPy
semantics exactly — jnp already implements them, so the registration layer
is thin by design; the value is the uniform dispatch surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _reg_fixed(name, fn, num_outputs=None, differentiable=True, eager=False):
    register(name, num_outputs=num_outputs, differentiable=differentiable,
             eager=eager)(fn)


# ---------------------------------------------------------------- unary ----
_UNARY = {
    "negative": jnp.negative, "reciprocal": jnp.reciprocal,
    "absolute": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log,
    "log10": jnp.log10, "log2": jnp.log2, "log1p": jnp.log1p,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "invert": jnp.invert, "logical_not": jnp.logical_not,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isposinf": jnp.isposinf,
    "isneginf": jnp.isneginf, "isfinite": jnp.isfinite,
    "conj": jnp.conj, "real": jnp.real, "imag": jnp.imag,
}
for _name, _fn in _UNARY.items():
    _reg_fixed(f"_npi_{_name}", _fn,
               differentiable=_name not in (
                   "invert", "logical_not", "isnan", "isinf", "isposinf",
                   "isneginf", "isfinite", "sign", "rint", "ceil", "floor",
                   "trunc", "fix"))

# --------------------------------------------------------------- binary ----
_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "true_divide": jnp.true_divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "fmod": jnp.fmod, "remainder": jnp.remainder,
    "power": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "hypot": jnp.hypot,
    "arctan2": jnp.arctan2, "copysign": jnp.copysign,
    "ldexp": jnp.ldexp, "logaddexp": jnp.logaddexp,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor, "left_shift": jnp.left_shift,
    "right_shift": jnp.right_shift,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "equal": jnp.equal, "not_equal": jnp.not_equal, "less": jnp.less,
    "less_equal": jnp.less_equal, "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "matmul": jnp.matmul, "dot": jnp.dot, "inner": jnp.inner,
    "outer": jnp.outer, "kron": jnp.kron, "cross": jnp.cross,
    "gcd": jnp.gcd, "lcm": jnp.lcm,
}
_NONDIFF_BIN = {"bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
                "right_shift", "logical_and", "logical_or", "logical_xor",
                "equal", "not_equal", "less", "less_equal", "greater",
                "greater_equal", "gcd", "lcm", "floor_divide"}
for _name, _fn in _BINARY.items():
    _reg_fixed(f"_npi_{_name}", _fn,
               differentiable=_name not in _NONDIFF_BIN)

# scalar variants (scalar baked static, like the legacy _*_scalar ops)
for _name in ("add", "subtract", "rsubtract", "multiply", "true_divide",
              "rtrue_divide", "mod", "rmod", "power", "rpower",
              "floor_divide", "rfloor_divide"):
    base = _name[1:] if _name.startswith("r") else _name
    rev = _name.startswith("r")
    fn = _BINARY[base]

    def _scalar_op(data, scalar=0.0, _fn=fn, _rev=rev):
        return _fn(scalar, data) if _rev else _fn(data, scalar)

    _reg_fixed(f"_npi_{_name}_scalar", _scalar_op,
               differentiable=base != "floor_divide")


# ----------------------------------------------------------- reductions ----
def _np_reduce(fn):
    def op(a, axis=None, keepdims=False, dtype=None):
        out = fn(a, axis=axis, keepdims=keepdims)
        return out.astype(dtype) if dtype is not None else out

    return op


_reg_fixed("_npi_sum", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.sum(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_prod", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.prod(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_mean", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.mean(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_std", lambda a, axis=None, ddof=0, keepdims=False:
           jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg_fixed("_npi_var", lambda a, axis=None, ddof=0, keepdims=False:
           jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg_fixed("_npi_max", _np_reduce(jnp.max))
_reg_fixed("_npi_min", _np_reduce(jnp.min))
_reg_fixed("_npi_amax", _np_reduce(jnp.max))
_reg_fixed("_npi_amin", _np_reduce(jnp.min))
_reg_fixed("_npi_argmax", lambda a, axis=None, keepdims=False:
           jnp.argmax(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_argmin", lambda a, axis=None, keepdims=False:
           jnp.argmin(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_any", lambda a, axis=None, keepdims=False:
           jnp.any(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_all", lambda a, axis=None, keepdims=False:
           jnp.all(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_cumsum", lambda a, axis=None, dtype=None:
           jnp.cumsum(a, axis=axis, dtype=dtype))
_reg_fixed("_npi_cumprod", lambda a, axis=None, dtype=None:
           jnp.cumprod(a, axis=axis, dtype=dtype))
_reg_fixed("_npi_nansum", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.nansum(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_nanprod", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.nanprod(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_median", lambda a, axis=None, keepdims=False:
           jnp.median(a, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_quantile", lambda a, q=0.5, axis=None, keepdims=False:
           jnp.quantile(a, q, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_percentile", lambda a, q=50.0, axis=None, keepdims=False:
           jnp.percentile(a, q, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_average",
           lambda a, weights=None, axis=None:
           jnp.average(a, axis=axis, weights=weights))
_reg_fixed("_npi_ptp", lambda a, axis=None, keepdims=False:
           jnp.ptp(a, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_count_nonzero", lambda a, axis=None, keepdims=False:
           jnp.count_nonzero(a, axis=axis, keepdims=keepdims),
           differentiable=False)


# ----------------------------------------------------------- shape/move ----
_reg_fixed("_npi_reshape", lambda a, newshape=(), order="C":
           jnp.reshape(a, newshape))
_reg_fixed("_npi_transpose", lambda a, axes=None:
           jnp.transpose(a, axes=axes if axes else None))
_reg_fixed("_npi_swapaxes", lambda a, dim1=0, dim2=1:
           jnp.swapaxes(a, dim1, dim2))
_reg_fixed("_npi_moveaxis", lambda a, source=0, destination=0:
           jnp.moveaxis(a, source, destination))
_reg_fixed("_npi_expand_dims", lambda a, axis=0: jnp.expand_dims(a, axis))
_reg_fixed("_npi_squeeze", lambda a, axis=None: jnp.squeeze(a, axis=axis))
_reg_fixed("_npi_broadcast_to", lambda a, shape=():
           jnp.broadcast_to(a, shape))
_reg_fixed("_npi_ravel", lambda a: jnp.ravel(a))
_reg_fixed("_npi_flip", lambda a, axis=None: jnp.flip(a, axis=axis))
_reg_fixed("_npi_fliplr", jnp.fliplr)
_reg_fixed("_npi_flipud", jnp.flipud)
_reg_fixed("_npi_roll", lambda a, shift=0, axis=None:
           jnp.roll(a, shift, axis=axis))
_reg_fixed("_npi_rot90", lambda a, k=1, axes=(0, 1):
           jnp.rot90(a, k=k, axes=tuple(axes)))
_reg_fixed("_npi_tile", lambda a, reps=(): jnp.tile(a, reps))
_reg_fixed("_npi_repeat", lambda a, repeats=1, axis=None:
           jnp.repeat(a, repeats, axis=axis))
_reg_fixed("_npi_pad", lambda a, pad_width=(), mode="constant",
           constant_values=0:
           jnp.pad(a, pad_width, mode=mode,
                   constant_values=constant_values)
           if mode == "constant" else jnp.pad(a, pad_width, mode=mode))
_reg_fixed("_npi_diag", lambda a, k=0: jnp.diag(a, k=k))
_reg_fixed("_npi_diagonal", lambda a, offset=0, axis1=0, axis2=1:
           jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2))
_reg_fixed("_npi_diagflat", lambda a, k=0: jnp.diagflat(a, k=k))
_reg_fixed("_npi_tril", lambda a, k=0: jnp.tril(a, k=k))
_reg_fixed("_npi_triu", lambda a, k=0: jnp.triu(a, k=k))
_reg_fixed("_npi_trace", lambda a, offset=0, axis1=0, axis2=1:
           jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2))


# ---------------------------------------------------------- combination ----
def _variadic(fn_name, jfn):
    def op(*arrays, axis=0):
        return jfn(arrays, axis=axis)

    _reg_fixed(fn_name, op)


_variadic("_npi_concatenate", jnp.concatenate)
_variadic("_npi_stack", jnp.stack)
_reg_fixed("_npi_vstack", lambda *arrays: jnp.vstack(arrays))
_reg_fixed("_npi_hstack", lambda *arrays: jnp.hstack(arrays))
_reg_fixed("_npi_dstack", lambda *arrays: jnp.dstack(arrays))
_reg_fixed("_npi_column_stack", lambda *arrays: jnp.column_stack(arrays))
_reg_fixed("_npi_atleast_1d", jnp.atleast_1d)
_reg_fixed("_npi_atleast_2d", jnp.atleast_2d)
_reg_fixed("_npi_atleast_3d", jnp.atleast_3d)
_reg_fixed("_npi_split", lambda a, indices_or_sections=1, axis=0:
           tuple(jnp.split(a, indices_or_sections, axis=axis)),
           num_outputs=2)  # variable; registry num_outputs unused for tuples
_reg_fixed("_npi_array_split", lambda a, indices_or_sections=1, axis=0:
           tuple(jnp.array_split(a, indices_or_sections, axis=axis)),
           num_outputs=2)
_reg_fixed("_npi_where", jnp.where)
_reg_fixed("_npi_clip", lambda a, a_min=None, a_max=None:
           jnp.clip(a, a_min, a_max))
_reg_fixed("_npi_take", lambda a, indices, axis=None, mode="clip":
           jnp.take(a, indices, axis=axis, mode=mode))
_reg_fixed("_npi_take_along_axis", lambda a, indices, axis=0:
           jnp.take_along_axis(a, indices, axis=axis))
_reg_fixed("_npi_searchsorted", lambda a, v, side="left":
           jnp.searchsorted(a, v, side=side), differentiable=False)
_reg_fixed("_npi_sort", lambda a, axis=-1: jnp.sort(a, axis=axis))
_reg_fixed("_npi_argsort", lambda a, axis=-1: jnp.argsort(a, axis=axis),
           differentiable=False)
# dynamic-output-shape ops: eager (never jitted; see Operator.eager)
_reg_fixed("_npi_unique", lambda a, size=None:
           jnp.unique(a, size=size), differentiable=False, eager=True)
_reg_fixed("_npi_nonzero", lambda a: tuple(jnp.nonzero(a)),
           num_outputs=2, differentiable=False, eager=True)
_reg_fixed("_npi_bincount", lambda a, weights=None, minlength=0:
           jnp.bincount(a, weights=weights, minlength=minlength),
           differentiable=False, eager=True)
_reg_fixed("_npi_histogram", lambda a, bins=10, range=None:
           jnp.histogram(a, bins=bins, range=range), num_outputs=2,
           differentiable=False)
_reg_fixed("_npi_interp", jnp.interp)
_reg_fixed("_npi_nan_to_num", lambda a, nan=0.0, posinf=None, neginf=None:
           jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf))
_reg_fixed("_npi_round", lambda a, decimals=0: jnp.round(a, decimals))
_reg_fixed("_npi_sign_nd", jnp.sign, differentiable=False)
_reg_fixed("_npi_meshgrid", lambda *arrays, indexing="xy":
           tuple(jnp.meshgrid(*arrays, indexing=indexing)), num_outputs=2)
_reg_fixed("_npi_tril_indices", lambda n=1, k=0, m=None:
           jnp.stack(jnp.tril_indices(n, k, m)), differentiable=False)
_reg_fixed("_npi_indices", lambda dimensions=(), dtype="int32":
           jnp.indices(tuple(dimensions), dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_diff", lambda a, n=1, axis=-1: jnp.diff(a, n=n, axis=axis))
_reg_fixed("_npi_ediff1d", lambda a: jnp.ediff1d(a))


def _gradient_op(a, axis=None):
    out = jnp.gradient(a, axis=axis)
    return tuple(out) if isinstance(out, list) else out


_reg_fixed("_npi_gradient_op", _gradient_op)


# ------------------------------------------------------ einsum/tensordot ----
def _einsum(*operands, subscripts=""):
    return jnp.einsum(subscripts, *operands)


_reg_fixed("_npi_einsum", _einsum)
_reg_fixed("_npi_tensordot", lambda a, b, axes=2:
           jnp.tensordot(a, b, axes=axes))
_reg_fixed("_npi_vdot", jnp.vdot)
_reg_fixed("_npi_tensordot_int_axes", lambda a, b, axes=2:
           jnp.tensordot(a, b, axes=int(axes)))


# ---------------------------------------------------------------- linalg ----
_reg_fixed("_npi_norm", lambda a, ord=None, axis=None, keepdims=False:
           jnp.linalg.norm(a, ord=ord, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_inv", jnp.linalg.inv)
_reg_fixed("_npi_pinv", lambda a, rcond=1e-15:
           jnp.linalg.pinv(a, rtol=rcond))
_reg_fixed("_npi_det", jnp.linalg.det)
_reg_fixed("_npi_slogdet", jnp.linalg.slogdet, num_outputs=2)
_reg_fixed("_npi_matrix_rank", lambda a, tol=None:
           jnp.linalg.matrix_rank(a, rtol=tol), differentiable=False)
_reg_fixed("_npi_svd", lambda a: tuple(jnp.linalg.svd(a)), num_outputs=3)
_reg_fixed("_npi_qr", lambda a: tuple(jnp.linalg.qr(a)), num_outputs=2)
_reg_fixed("_npi_cholesky", jnp.linalg.cholesky)
_reg_fixed("_npi_eig", lambda a: tuple(jnp.linalg.eig(a)), num_outputs=2,
           differentiable=False)
_reg_fixed("_npi_eigh", lambda a, UPLO="L":
           tuple(jnp.linalg.eigh(a, UPLO=UPLO)), num_outputs=2)
_reg_fixed("_npi_eigvals", jnp.linalg.eigvals, differentiable=False)
_reg_fixed("_npi_eigvalsh", lambda a, UPLO="L":
           jnp.linalg.eigvalsh(a, UPLO=UPLO))
_reg_fixed("_npi_solve", jnp.linalg.solve)
_reg_fixed("_npi_lstsq", lambda a, b, rcond=None:
           tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), num_outputs=4,
           differentiable=False)
_reg_fixed("_npi_matrix_power", lambda a, n=1: jnp.linalg.matrix_power(a, n))
_reg_fixed("_npi_multi_dot", lambda *arrays: jnp.linalg.multi_dot(arrays))


# ---------------------------------------------------------------- random ----
_reg_fixed("_npi_random_uniform",
           lambda low=0.0, high=1.0, key=None, size=(), dtype="float32":
           jax.random.uniform(key, shape=tuple(size),
                              dtype=jnp.dtype(dtype), minval=low,
                              maxval=high),
           differentiable=False)
_reg_fixed("_npi_random_normal",
           lambda loc=0.0, scale=1.0, key=None, size=(), dtype="float32":
           loc + scale * jax.random.normal(key, shape=tuple(size),
                                           dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_randint",
           lambda low=0, high=None, key=None, size=(), dtype="int32":
           jax.random.randint(key, tuple(size), low,
                              high if high is not None else low,
                              dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_choice",
           lambda a, key=None, size=(), replace=True, p=None:
           jax.random.choice(key, a, shape=tuple(size), replace=replace,
                             p=p),
           differentiable=False)
_reg_fixed("_npi_random_permutation",
           lambda a, key=None: jax.random.permutation(key, a),
           differentiable=False)
_reg_fixed("_npi_random_gamma",
           lambda shape_param=1.0, scale=1.0, key=None, size=(),
           dtype="float32":
           scale * jax.random.gamma(key, shape_param, shape=tuple(size),
                                    dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_exponential",
           lambda scale=1.0, key=None, size=(), dtype="float32":
           scale * jax.random.exponential(key, shape=tuple(size),
                                          dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_beta",
           lambda a=1.0, b=1.0, key=None, size=(), dtype="float32":
           jax.random.beta(key, a, b, shape=tuple(size),
                           dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_poisson",
           lambda lam=1.0, key=None, size=(), dtype="int32":
           jax.random.poisson(key, lam, shape=tuple(size),
                              dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_bernoulli",
           lambda p=0.5, key=None, size=(), dtype="float32":
           jax.random.bernoulli(key, p, shape=tuple(size))
           .astype(jnp.dtype(dtype)),
           differentiable=False)
