"""NumPy-frontend ops: `_npi_*` registrations.

Parity target: `src/operator/numpy/` (~33.5k LoC, 147 `_npi_*`
registrations: np_elemwise_broadcast_op.cc, np_matrix_op.cc,
np_einsum_op.cc, np_tensordot_op.cc, linalg/*, random/*). Each op here is
the jnp emitter for one `_npi_` name; `mx.np` functions dispatch through
the registry so the tape, AMP pass, profiler and opperf all see them like
any other op.

Unlike the legacy op set (MXNet 1.x semantics), these follow NumPy
semantics exactly — jnp already implements them, so the registration layer
is thin by design; the value is the uniform dispatch surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _reg_fixed(name, fn, num_outputs=None, differentiable=True, eager=False):
    register(name, num_outputs=num_outputs, differentiable=differentiable,
             eager=eager)(fn)


# ---------------------------------------------------------------- unary ----
_UNARY = {
    "negative": jnp.negative, "reciprocal": jnp.reciprocal,
    "absolute": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log,
    "log10": jnp.log10, "log2": jnp.log2, "log1p": jnp.log1p,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "invert": jnp.invert, "logical_not": jnp.logical_not,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isposinf": jnp.isposinf,
    "isneginf": jnp.isneginf, "isfinite": jnp.isfinite,
    "conj": jnp.conj, "real": jnp.real, "imag": jnp.imag,
}
for _name, _fn in _UNARY.items():
    _reg_fixed(f"_npi_{_name}", _fn,
               differentiable=_name not in (
                   "invert", "logical_not", "isnan", "isinf", "isposinf",
                   "isneginf", "isfinite", "sign", "rint", "ceil", "floor",
                   "trunc", "fix"))

# --------------------------------------------------------------- binary ----
_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "true_divide": jnp.true_divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "fmod": jnp.fmod, "remainder": jnp.remainder,
    "power": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "hypot": jnp.hypot,
    "arctan2": jnp.arctan2, "copysign": jnp.copysign,
    "ldexp": jnp.ldexp, "logaddexp": jnp.logaddexp,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor, "left_shift": jnp.left_shift,
    "right_shift": jnp.right_shift,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "equal": jnp.equal, "not_equal": jnp.not_equal, "less": jnp.less,
    "less_equal": jnp.less_equal, "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "matmul": jnp.matmul, "dot": jnp.dot, "inner": jnp.inner,
    "outer": jnp.outer, "kron": jnp.kron, "cross": jnp.cross,
    "gcd": jnp.gcd, "lcm": jnp.lcm,
}
_NONDIFF_BIN = {"bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
                "right_shift", "logical_and", "logical_or", "logical_xor",
                "equal", "not_equal", "less", "less_equal", "greater",
                "greater_equal", "gcd", "lcm", "floor_divide"}
for _name, _fn in _BINARY.items():
    _reg_fixed(f"_npi_{_name}", _fn,
               differentiable=_name not in _NONDIFF_BIN)

# scalar variants (scalar baked static, like the legacy _*_scalar ops)
for _name in ("add", "subtract", "rsubtract", "multiply", "true_divide",
              "rtrue_divide", "mod", "rmod", "power", "rpower",
              "floor_divide", "rfloor_divide"):
    base = _name[1:] if _name.startswith("r") else _name
    rev = _name.startswith("r")
    fn = _BINARY[base]

    def _scalar_op(data, scalar=0.0, _fn=fn, _rev=rev):
        return _fn(scalar, data) if _rev else _fn(data, scalar)

    _reg_fixed(f"_npi_{_name}_scalar", _scalar_op,
               differentiable=base != "floor_divide")


# ----------------------------------------------------------- reductions ----
def _np_reduce(fn):
    def op(a, axis=None, keepdims=False, dtype=None):
        out = fn(a, axis=axis, keepdims=keepdims)
        return out.astype(dtype) if dtype is not None else out

    return op


_reg_fixed("_npi_sum", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.sum(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_prod", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.prod(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_mean", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.mean(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_std", lambda a, axis=None, ddof=0, keepdims=False:
           jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg_fixed("_npi_var", lambda a, axis=None, ddof=0, keepdims=False:
           jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg_fixed("_npi_max", _np_reduce(jnp.max))
_reg_fixed("_npi_min", _np_reduce(jnp.min))
_reg_fixed("_npi_amax", _np_reduce(jnp.max))
_reg_fixed("_npi_amin", _np_reduce(jnp.min))
_reg_fixed("_npi_argmax", lambda a, axis=None, keepdims=False:
           jnp.argmax(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_argmin", lambda a, axis=None, keepdims=False:
           jnp.argmin(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_any", lambda a, axis=None, keepdims=False:
           jnp.any(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_all", lambda a, axis=None, keepdims=False:
           jnp.all(a, axis=axis, keepdims=keepdims), differentiable=False)
_reg_fixed("_npi_cumsum", lambda a, axis=None, dtype=None:
           jnp.cumsum(a, axis=axis, dtype=dtype))
_reg_fixed("_npi_cumprod", lambda a, axis=None, dtype=None:
           jnp.cumprod(a, axis=axis, dtype=dtype))
_reg_fixed("_npi_nansum", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.nansum(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_nanprod", lambda a, axis=None, dtype=None, keepdims=False:
           jnp.nanprod(a, axis=axis, dtype=dtype, keepdims=keepdims))
_reg_fixed("_npi_median", lambda a, axis=None, keepdims=False:
           jnp.median(a, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_quantile", lambda a, q=0.5, axis=None, keepdims=False:
           jnp.quantile(a, q, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_percentile", lambda a, q=50.0, axis=None, keepdims=False:
           jnp.percentile(a, q, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_average",
           lambda a, weights=None, axis=None:
           jnp.average(a, axis=axis, weights=weights))
_reg_fixed("_npi_ptp", lambda a, axis=None, keepdims=False:
           jnp.ptp(a, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_count_nonzero", lambda a, axis=None, keepdims=False:
           jnp.count_nonzero(a, axis=axis, keepdims=keepdims),
           differentiable=False)


# ----------------------------------------------------------- shape/move ----
_reg_fixed("_npi_reshape", lambda a, newshape=(), order="C":
           jnp.reshape(a, newshape))
_reg_fixed("_npi_transpose", lambda a, axes=None:
           jnp.transpose(a, axes=axes if axes else None))
_reg_fixed("_npi_swapaxes", lambda a, dim1=0, dim2=1:
           jnp.swapaxes(a, dim1, dim2))
_reg_fixed("_npi_moveaxis", lambda a, source=0, destination=0:
           jnp.moveaxis(a, source, destination))
_reg_fixed("_npi_expand_dims", lambda a, axis=0: jnp.expand_dims(a, axis))
_reg_fixed("_npi_squeeze", lambda a, axis=None: jnp.squeeze(a, axis=axis))
_reg_fixed("_npi_broadcast_to", lambda a, shape=():
           jnp.broadcast_to(a, shape))
_reg_fixed("_npi_ravel", lambda a: jnp.ravel(a))
_reg_fixed("_npi_flip", lambda a, axis=None: jnp.flip(a, axis=axis))
_reg_fixed("_npi_fliplr", jnp.fliplr)
_reg_fixed("_npi_flipud", jnp.flipud)
_reg_fixed("_npi_roll", lambda a, shift=0, axis=None:
           jnp.roll(a, shift, axis=axis))
_reg_fixed("_npi_rot90", lambda a, k=1, axes=(0, 1):
           jnp.rot90(a, k=k, axes=tuple(axes)))
_reg_fixed("_npi_tile", lambda a, reps=(): jnp.tile(a, reps))
_reg_fixed("_npi_repeat", lambda a, repeats=1, axis=None:
           jnp.repeat(a, repeats, axis=axis))
_reg_fixed("_npi_pad", lambda a, pad_width=(), mode="constant",
           constant_values=0:
           jnp.pad(a, pad_width, mode=mode,
                   constant_values=constant_values)
           if mode == "constant" else jnp.pad(a, pad_width, mode=mode))
_reg_fixed("_npi_diag", lambda a, k=0: jnp.diag(a, k=k))
_reg_fixed("_npi_diagonal", lambda a, offset=0, axis1=0, axis2=1:
           jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2))
_reg_fixed("_npi_diagflat", lambda a, k=0: jnp.diagflat(a, k=k))
_reg_fixed("_npi_tril", lambda a, k=0: jnp.tril(a, k=k))
_reg_fixed("_npi_triu", lambda a, k=0: jnp.triu(a, k=k))
_reg_fixed("_npi_trace", lambda a, offset=0, axis1=0, axis2=1:
           jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2))


# ---------------------------------------------------------- combination ----
def _variadic(fn_name, jfn):
    def op(*arrays, axis=0):
        return jfn(arrays, axis=axis)

    _reg_fixed(fn_name, op)


_variadic("_npi_concatenate", jnp.concatenate)
_variadic("_npi_stack", jnp.stack)
_reg_fixed("_npi_vstack", lambda *arrays: jnp.vstack(arrays))
_reg_fixed("_npi_hstack", lambda *arrays: jnp.hstack(arrays))
_reg_fixed("_npi_dstack", lambda *arrays: jnp.dstack(arrays))
_reg_fixed("_npi_column_stack", lambda *arrays: jnp.column_stack(arrays))
_reg_fixed("_npi_atleast_1d", jnp.atleast_1d)
_reg_fixed("_npi_atleast_2d", jnp.atleast_2d)
_reg_fixed("_npi_atleast_3d", jnp.atleast_3d)
_reg_fixed("_npi_split", lambda a, indices_or_sections=1, axis=0:
           tuple(jnp.split(a, indices_or_sections, axis=axis)),
           num_outputs=2)  # variable; registry num_outputs unused for tuples
_reg_fixed("_npi_array_split", lambda a, indices_or_sections=1, axis=0:
           tuple(jnp.array_split(a, indices_or_sections, axis=axis)),
           num_outputs=2)
_reg_fixed("_npi_where", jnp.where)
_reg_fixed("_npi_clip", lambda a, a_min=None, a_max=None:
           jnp.clip(a, a_min, a_max))
_reg_fixed("_npi_take", lambda a, indices, axis=None, mode="clip":
           jnp.take(a, indices, axis=axis, mode=mode))
_reg_fixed("_npi_take_along_axis", lambda a, indices, axis=0:
           jnp.take_along_axis(a, indices, axis=axis))
_reg_fixed("_npi_searchsorted", lambda a, v, side="left":
           jnp.searchsorted(a, v, side=side), differentiable=False)
_reg_fixed("_npi_sort", lambda a, axis=-1: jnp.sort(a, axis=axis))
_reg_fixed("_npi_argsort", lambda a, axis=-1: jnp.argsort(a, axis=axis),
           differentiable=False)
# dynamic-output-shape ops: eager (never jitted; see Operator.eager)
_reg_fixed("_npi_unique", lambda a, size=None:
           jnp.unique(a, size=size), differentiable=False, eager=True)
_reg_fixed("_npi_nonzero", lambda a: tuple(jnp.nonzero(a)),
           num_outputs=2, differentiable=False, eager=True)
_reg_fixed("_npi_bincount", lambda a, weights=None, minlength=0:
           jnp.bincount(a, weights=weights, minlength=minlength),
           differentiable=False, eager=True)
_reg_fixed("_npi_histogram", lambda a, bins=10, range=None:
           jnp.histogram(a, bins=bins, range=range), num_outputs=2,
           differentiable=False)
_reg_fixed("_npi_interp", jnp.interp)
_reg_fixed("_npi_nan_to_num", lambda a, nan=0.0, posinf=None, neginf=None:
           jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf))
_reg_fixed("_npi_round", lambda a, decimals=0: jnp.round(a, decimals))
_reg_fixed("_npi_sign_nd", jnp.sign, differentiable=False)
_reg_fixed("_npi_meshgrid", lambda *arrays, indexing="xy":
           tuple(jnp.meshgrid(*arrays, indexing=indexing)), num_outputs=2)
_reg_fixed("_npi_tril_indices", lambda n=1, k=0, m=None:
           jnp.stack(jnp.tril_indices(n, k, m)), differentiable=False)
_reg_fixed("_npi_indices", lambda dimensions=(), dtype="int32":
           jnp.indices(tuple(dimensions), dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_diff", lambda a, n=1, axis=-1: jnp.diff(a, n=n, axis=axis))
_reg_fixed("_npi_ediff1d", lambda a: jnp.ediff1d(a))


def _gradient_op(a, axis=None):
    out = jnp.gradient(a, axis=axis)
    return tuple(out) if isinstance(out, list) else out


_reg_fixed("_npi_gradient_op", _gradient_op)


# ------------------------------------------------------ einsum/tensordot ----
def _einsum(*operands, subscripts=""):
    return jnp.einsum(subscripts, *operands)


_reg_fixed("_npi_einsum", _einsum)
_reg_fixed("_npi_tensordot", lambda a, b, axes=2:
           jnp.tensordot(a, b, axes=axes))
_reg_fixed("_npi_vdot", jnp.vdot)
_reg_fixed("_npi_tensordot_int_axes", lambda a, b, axes=2:
           jnp.tensordot(a, b, axes=int(axes)))


# ---------------------------------------------------------------- linalg ----
_reg_fixed("_npi_norm", lambda a, ord=None, axis=None, keepdims=False:
           jnp.linalg.norm(a, ord=ord, axis=axis, keepdims=keepdims))
_reg_fixed("_npi_inv", jnp.linalg.inv)
_reg_fixed("_npi_pinv", lambda a, rcond=1e-15:
           jnp.linalg.pinv(a, rtol=rcond))
_reg_fixed("_npi_det", jnp.linalg.det)
_reg_fixed("_npi_slogdet", jnp.linalg.slogdet, num_outputs=2)
_reg_fixed("_npi_matrix_rank", lambda a, tol=None:
           jnp.linalg.matrix_rank(a, rtol=tol), differentiable=False)
_reg_fixed("_npi_svd", lambda a: tuple(jnp.linalg.svd(a)), num_outputs=3)
_reg_fixed("_npi_qr", lambda a: tuple(jnp.linalg.qr(a)), num_outputs=2)
_reg_fixed("_npi_cholesky", jnp.linalg.cholesky)
_reg_fixed("_npi_eig", lambda a: tuple(jnp.linalg.eig(a)), num_outputs=2,
           differentiable=False)
_reg_fixed("_npi_eigh", lambda a, UPLO="L":
           tuple(jnp.linalg.eigh(a, UPLO=UPLO)), num_outputs=2)
_reg_fixed("_npi_eigvals", jnp.linalg.eigvals, differentiable=False)
_reg_fixed("_npi_eigvalsh", lambda a, UPLO="L":
           jnp.linalg.eigvalsh(a, UPLO=UPLO))
_reg_fixed("_npi_solve", jnp.linalg.solve)
_reg_fixed("_npi_lstsq", lambda a, b, rcond=None:
           tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), num_outputs=4,
           differentiable=False)
_reg_fixed("_npi_matrix_power", lambda a, n=1: jnp.linalg.matrix_power(a, n))
_reg_fixed("_npi_multi_dot", lambda *arrays: jnp.linalg.multi_dot(arrays))


# ---------------------------------------------------------------- random ----
_reg_fixed("_npi_random_uniform",
           lambda low=0.0, high=1.0, key=None, size=(), dtype="float32":
           jax.random.uniform(key, shape=tuple(size),
                              dtype=jnp.dtype(dtype), minval=low,
                              maxval=high),
           differentiable=False)
_reg_fixed("_npi_random_normal",
           lambda loc=0.0, scale=1.0, key=None, size=(), dtype="float32":
           loc + scale * jax.random.normal(key, shape=tuple(size),
                                           dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_randint",
           lambda low=0, high=None, key=None, size=(), dtype="int32":
           jax.random.randint(key, tuple(size), low,
                              high if high is not None else low,
                              dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_choice",
           lambda a, key=None, size=(), replace=True, p=None:
           jax.random.choice(key, a, shape=tuple(size), replace=replace,
                             p=p),
           differentiable=False)
_reg_fixed("_npi_random_permutation",
           lambda a, key=None: jax.random.permutation(key, a),
           differentiable=False)
_reg_fixed("_npi_random_gamma",
           lambda shape_param=1.0, scale=1.0, key=None, size=(),
           dtype="float32":
           scale * jax.random.gamma(key, shape_param, shape=tuple(size),
                                    dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_exponential",
           lambda scale=1.0, key=None, size=(), dtype="float32":
           scale * jax.random.exponential(key, shape=tuple(size),
                                          dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_beta",
           lambda a=1.0, b=1.0, key=None, size=(), dtype="float32":
           jax.random.beta(key, a, b, shape=tuple(size),
                           dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_poisson",
           lambda lam=1.0, key=None, size=(), dtype="int32":
           jax.random.poisson(key, lam, shape=tuple(size),
                              dtype=jnp.dtype(dtype)),
           differentiable=False)
_reg_fixed("_npi_random_bernoulli",
           lambda p=0.5, key=None, size=(), dtype="float32":
           jax.random.bernoulli(key, p, shape=tuple(size))
           .astype(jnp.dtype(dtype)),
           differentiable=False)


# ----------------------------------------------------------- npi tail ------
# parity: the remaining src/operator/numpy registrations (np_init_op.cc,
# np_window_op.cc, np_insert/delete, random/*, linalg tensorinv/solve,
# npx_*). Aliases keep the reference's exact `_npi_`/`_np_` names resolving
# to the one emitter each.

from .registry import get as _get


def _alias(new, existing):
    op = _get(existing)
    register(new, num_outputs=op.num_outputs,
             differentiable=op.differentiable, eager=op.eager)(op.fn)


for _new, _old in [
        ("_np_all", "_npi_all"), ("_np_any", "_npi_any"),
        ("_np_cumsum", "_npi_cumsum"), ("_np_diag", "_npi_diag"),
        ("_np_diagflat", "_npi_diagflat"),
        ("_np_diagonal", "_npi_diagonal"), ("_np_dot", "_npi_dot"),
        ("_np_moveaxis", "_npi_moveaxis"), ("_np_reshape", "_npi_reshape"),
        ("_np_roll", "_npi_roll"), ("_np_squeeze", "_npi_squeeze"),
        ("_np_trace", "_npi_trace"), ("_np_transpose", "_npi_transpose"),
        ("_npi_bitwise_not", "_npi_invert"),
        ("_npi_normal", "_npi_random_normal"),
        ("_npi_uniform", "_npi_random_uniform"),
        ("_npi_bernoulli", "_npi_random_bernoulli"),
        ("_npi_exponential", "_npi_random_exponential"),
        ("_npi_gamma", "_npi_random_gamma"),
        ("_npi_choice", "_npi_random_choice"),
]:
    _alias(_new, _old)


@register("_npi_multinomial", differentiable=False)
def _npi_multinomial(pvals=None, n=1, key=None, size=()):
    """parity: np_random multinomial — counts over categories from `n`
    draws with probabilities `pvals` (categorical draws + one-hot sum)."""
    pvals = jnp.asarray(pvals)
    k = pvals.shape[-1]
    draws = jax.random.categorical(
        key, jnp.log(jnp.maximum(pvals, 1e-38)),
        shape=tuple(size) + (int(n),) if size else (int(n),))
    return jnp.sum(jax.nn.one_hot(draws, k, dtype=jnp.int64), axis=-2)

_reg_fixed("_npi_around", jnp.round)
_reg_fixed("_npi_deg2rad", jnp.deg2rad)
_reg_fixed("_npi_rad2deg", jnp.rad2deg)
_reg_fixed("_np_copy", lambda x: jnp.array(x))


@register("_npi_hanning", differentiable=False)
def _npi_hanning(M=0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.hanning(int(M)).astype(canonical_dtype(dtype))


@register("_npi_hamming", differentiable=False)
def _npi_hamming(M=0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.hamming(int(M)).astype(canonical_dtype(dtype))


@register("_npi_blackman", differentiable=False)
def _npi_blackman(M=0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.blackman(int(M)).astype(canonical_dtype(dtype))


@register("_npi_logspace", differentiable=False)
def _npi_logspace(start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
                  dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.logspace(start, stop, int(num), endpoint=endpoint,
                        base=base).astype(canonical_dtype(dtype))


@register("_npi_polyval")
def _npi_polyval(p, x):
    return jnp.polyval(p, x)


@register("_npi_ediff1d")
def _npi_ediff1d(data, to_begin=None, to_end=None):
    d = jnp.diff(data.reshape(-1))
    parts = []
    if to_begin is not None:
        parts.append(jnp.atleast_1d(jnp.asarray(to_begin, d.dtype)).reshape(-1))
    parts.append(d)
    if to_end is not None:
        parts.append(jnp.atleast_1d(jnp.asarray(to_end, d.dtype)).reshape(-1))
    return jnp.concatenate(parts) if len(parts) > 1 else d


@register("_npi_delete", eager=True, differentiable=False)
def _npi_delete(data, obj=None, start=None, stop=None, step=None, axis=None):
    import numpy as onp

    arr = onp.asarray(data)
    if obj is None:
        obj = slice(start, stop, step)
    elif hasattr(obj, "shape"):
        obj = onp.asarray(obj).astype(onp.int64)
    else:
        obj = int(obj)
    return jnp.asarray(onp.delete(arr, obj, axis=axis))


@register("_npi_insert_scalar", eager=True, differentiable=False)
def _npi_insert_scalar(data, obj=None, val=0.0, axis=None):
    import numpy as onp

    return jnp.asarray(onp.insert(onp.asarray(data), int(obj), val,
                                  axis=axis))


@register("_npi_insert_slice", eager=True, differentiable=False)
def _npi_insert_slice(data, values, start=None, stop=None, step=None,
                      axis=None):
    import numpy as onp

    return jnp.asarray(onp.insert(onp.asarray(data),
                                  slice(start, stop, step),
                                  onp.asarray(values), axis=axis))


@register("_npi_insert_tensor", eager=True, differentiable=False)
def _npi_insert_tensor(data, obj, values, axis=None):
    import numpy as onp

    return jnp.asarray(onp.insert(onp.asarray(data),
                                  onp.asarray(obj).astype(onp.int64),
                                  onp.asarray(values), axis=axis))


@register("_npi_diag_indices_from", differentiable=False)
def _npi_diag_indices_from(data):
    return jnp.stack(jnp.diag_indices(data.shape[0], data.ndim))


def _hsplit_n(n_in, kw):
    ios = kw.get("indices_or_sections", 1)
    return int(ios) if not isinstance(ios, (tuple, list)) else len(ios) + 1


@register("_npi_hsplit", num_outputs=_hsplit_n)
def _npi_hsplit(data, indices_or_sections=1):
    return tuple(jnp.split(data, indices_or_sections
                           if not isinstance(indices_or_sections, (tuple, list))
                           else list(indices_or_sections),
                           axis=1 if data.ndim > 1 else 0))


@register("_npi_dsplit", num_outputs=_hsplit_n)
def _npi_dsplit(data, indices_or_sections=1):
    return tuple(jnp.split(data, indices_or_sections
                           if not isinstance(indices_or_sections, (tuple, list))
                           else list(indices_or_sections), axis=2))


@register("_npi_vsplit", num_outputs=_hsplit_n)
def _npi_vsplit(data, indices_or_sections=1):
    return tuple(jnp.split(data, indices_or_sections
                           if not isinstance(indices_or_sections, (tuple, list))
                           else list(indices_or_sections), axis=0))


# creation ops (np_init_op.cc)

@register("_npi_zeros", differentiable=False)
def _npi_zeros(shape=(), dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.zeros(tuple(shape), canonical_dtype(dtype))


@register("_npi_ones", differentiable=False)
def _npi_ones(shape=(), dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.ones(tuple(shape), canonical_dtype(dtype))


@register("_npi_full", differentiable=False, aliases=("_npi_full_like",))
def _npi_full(a=None, shape=(), fill_value=0.0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    if a is not None:
        return jnp.full_like(a, fill_value)
    return jnp.full(tuple(shape), fill_value, canonical_dtype(dtype))


@register("_npi_arange", differentiable=False)
def _npi_arange(start=0.0, stop=None, step=1.0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    if stop is None:
        start, stop = 0.0, start
    return jnp.arange(start, stop, step, canonical_dtype(dtype))


@register("_npi_linspace", differentiable=False)
def _npi_linspace(start=0.0, stop=1.0, num=50, endpoint=True,
                  dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.linspace(start, stop, int(num),
                        endpoint=endpoint).astype(canonical_dtype(dtype))


@register("_npi_eye", differentiable=False,
          aliases=("_npi_identity", "_eye"))
def _npi_eye(N=1, M=None, k=0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.eye(int(N), None if M is None else int(M), int(k),
                   dtype=canonical_dtype(dtype))


@register("_npi_tensorinv")
def _npi_tensorinv(a, ind=2):
    return jnp.linalg.tensorinv(a, ind=ind)


@register("_npi_tensorsolve")
def _npi_tensorsolve(a, b, a_axes=None):
    return jnp.linalg.tensorsolve(a, b, axes=tuple(a_axes) if a_axes else None)


@register("_npi_pinv_scalar_rcond")
def _npi_pinv_scalar_rcond(a, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian)


@register("_npx_nonzero", eager=True, differentiable=False)
def _npx_nonzero(data):
    import numpy as onp

    return jnp.asarray(onp.stack(onp.nonzero(onp.asarray(data)),
                                 axis=-1).astype(onp.int64))


@register("_npx_constraint_check", differentiable=False)
def _npx_constraint_check(data, msg="constraint violated"):
    """parity: npx_constraint_check.cc — passes data through when every
    element is true; the framework surfaces `msg` at the sync point
    otherwise (jax error-check semantics: returns bool scalar)."""
    return jnp.all(data.astype(bool))


@register("_npx_reshape")
def _npx_reshape(data, newshape=(), reverse=False, order="C"):
    """parity: npx_reshape special codes — -1 infer one dim, -2 copy all
    remaining source dims, -3 merge the next two source dims, -4 split one
    source dim into the next two newshape entries, -5 merge all remaining
    source dims. A source cursor advances as codes consume dims."""
    src = list(data.shape)
    tgt = []
    cursor = 0
    codes = list(newshape)
    i = 0
    while i < len(codes):
        s = codes[i]
        if s == -2:
            tgt.extend(src[cursor:])
            cursor = len(src)
        elif s == -3:
            tgt.append(src[cursor] * src[cursor + 1])
            cursor += 2
        elif s == -4:
            d1, d2 = codes[i + 1], codes[i + 2]
            whole = src[cursor]
            if d1 == -1:
                d1 = whole // d2
            if d2 == -1:
                d2 = whole // d1
            tgt.extend([int(d1), int(d2)])
            cursor += 1
            i += 2
        elif s == -5:
            prod = 1
            for d in src[cursor:]:
                prod *= d
            tgt.append(prod)
            cursor = len(src)
        elif s == -1:
            tgt.append(-1)
            cursor += 1
        else:
            tgt.append(int(s))
            cursor += 1
        i += 1
    return jnp.reshape(data, tuple(tgt))


@register("_npi_share_memory", eager=True, differentiable=False)
def _npi_share_memory(a, b):
    """XLA buffers never alias across arrays from Python's view."""
    return jnp.asarray(False)


@register("_npi_lcm_scalar", differentiable=False)
def _npi_lcm_scalar(data, scalar=1):
    return jnp.lcm(data.astype(jnp.int64), jnp.asarray(int(scalar)))


@register("_npi_bitwise_and_scalar", differentiable=False)
def _npi_bitwise_and_scalar(data, scalar=0):
    return jnp.bitwise_and(data.astype(jnp.int64), int(scalar))


@register("_npi_bitwise_or_scalar", differentiable=False)
def _npi_bitwise_or_scalar(data, scalar=0):
    return jnp.bitwise_or(data.astype(jnp.int64), int(scalar))


@register("_npi_bitwise_xor_scalar", differentiable=False)
def _npi_bitwise_xor_scalar(data, scalar=0):
    return jnp.bitwise_xor(data.astype(jnp.int64), int(scalar))


@register("_npi_where_lscalar")
def _npi_where_lscalar(cond, x, scalar=0.0):
    return jnp.where(cond.astype(bool), x, scalar)


@register("_npi_where_rscalar")
def _npi_where_rscalar(cond, y, scalar=0.0):
    return jnp.where(cond.astype(bool), scalar, y)


@register("_npi_where_scalar2")
def _npi_where_scalar2(cond, lscalar=0.0, rscalar=0.0):
    return jnp.where(cond.astype(bool), lscalar, rscalar)


@register("_npi_boolean_mask_assign_scalar")
def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(bool), value, data)


@register("_npi_boolean_mask_assign_tensor")
def _npi_boolean_mask_assign_tensor(data, mask, value):
    return jnp.where(mask.astype(bool), value, data)


# remaining reference sampler names (np_random ops) + tail distributions
_reg_fixed("_npi_pareto",
           lambda a=1.0, key=None, size=(), dtype="float32":
           (jnp.exp(jax.random.exponential(key, shape=tuple(size),
                                           dtype=jnp.dtype(dtype)) / a)
            - 1.0),
           differentiable=False)
_reg_fixed("_npi_weibull",
           lambda a=1.0, key=None, size=(), dtype="float32":
           jnp.power(jax.random.exponential(key, shape=tuple(size),
                                            dtype=jnp.dtype(dtype)),
                     1.0 / a),
           differentiable=False)
_reg_fixed("_npi_rayleigh",
           lambda scale=1.0, key=None, size=(), dtype="float32":
           scale * jnp.sqrt(2.0 * jax.random.exponential(
               key, shape=tuple(size), dtype=jnp.dtype(dtype))),
           differentiable=False)
# *_n variants: shape given as the size of an extra leading batch
# (np_random ops `normal_n`/`uniform_n` used by mx.np.random with out=)
_alias("_npi_normal_n", "_npi_random_normal")
_alias("_npi_uniform_n", "_npi_random_uniform")
_reg_fixed("_npi_powerd",
           lambda a=1.0, key=None, size=(), dtype="float32":
           jnp.power(jax.random.uniform(key, shape=tuple(size),
                                        dtype=jnp.dtype(dtype)), 1.0 / a),
           differentiable=False)

# legacy internal names for ravel/unravel/split_v2 (matrix_op.cc/ravel.cc
# register the underscore forms)
_alias("_unravel_index", "unravel_index")
_alias("_ravel_multi_index", "ravel_multi_index")
_alias("_split_v2", "split_v2")
