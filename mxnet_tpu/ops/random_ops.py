"""Random sampling ops.

Parity target: `src/operator/random/` (uniform/normal/gamma/poisson/
multinomial/negbinomial samplers over the per-device RandGenerator).

Every op takes an explicit PRNG `key` as its first array argument; the
imperative frontend supplies `mxnet_tpu.random.next_key()` and hybridized
graphs thread keys as traced inputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import canonical_dtype


@register("_random_uniform", differentiable=False, aliases=("uniform",))
def _uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(key, tuple(shape), canonical_dtype(dtype), low, high)


@register("_random_normal", differentiable=False, aliases=("normal",))
def _normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, tuple(shape), canonical_dtype(dtype))


@register("_random_gamma", differentiable=False)
def _gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(key, alpha, tuple(shape), canonical_dtype(dtype))


@register("_random_exponential", differentiable=False)
def _exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, tuple(shape), canonical_dtype(dtype)) / lam


@register("_random_poisson", differentiable=False)
def _poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, tuple(shape)).astype(canonical_dtype(dtype))


@register("_random_negative_binomial", differentiable=False)
def _neg_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(canonical_dtype(dtype))


@register("_random_randint", differentiable=False)
def _randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, tuple(shape), low, high, canonical_dtype(dtype))


@register("_sample_multinomial", differentiable=False)
def _multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    # `shape` is a static hyper-param: derive the draw count from the
    # Python tuple, not a traced array (int(jnp.prod(...)) breaks jit)
    n = 1
    for d in tuple(shape):
        n *= int(d)
    logits = jnp.log(jnp.clip(data, 1e-30, None))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        out = out.reshape(tuple(shape) if shape else ())
    else:
        out = jax.random.categorical(key, logits[:, None, :].repeat(n, 1), axis=-1)
        out = out.reshape((data.shape[0],) + (tuple(shape) if shape else ()))
    return out.astype(canonical_dtype(dtype))


@register("_shuffle", differentiable=False)
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_random_bernoulli", differentiable=False)
def _bernoulli(key, p=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(canonical_dtype(dtype))
