"""Optimizer update steps as fused ops.

Parity target: `src/operator/optimizer_op.cc:49-970` — the reference makes
every optimizer update a *registered op* (sgd_update, sgd_mom_update,
mp_sgd_update (multi-precision), adam_update, ftml, lamb_update_phase1/2, …)
so updates run fused on-device without Python in the loop.

TPU-native: each update is a pure function over (weight, grad, states...)
returning the new tensors; the registry jits one executable per
(op, hyper-params) pair, and `multi_*` aggregated variants are realised by
the Trainer jitting a single update over the whole parameter pytree (better
than the reference's fixed-size multi-tensor kernels — XLA fuses across the
whole step).

All updates honour rescale_grad / clip_gradient / wd exactly as the
reference kernels do.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd):
    """Adam-family gradient prep: fold wd*weight in BEFORE clipping.

    The reference's AdamUpdateKernel (and ftml/rmsprop/rmspropalex,
    optimizer_op-inl.h:1215,1303,1966,2064) computes
    grad = rescale*grad + wd*weight and clips the sum; the SGD-family
    kernels clip first. Preserving the ordering keeps numerics identical
    whenever clip_gradient is set with nonzero wd.
    """
    g = grad * rescale_grad + wd * weight
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight)
    return weight + mom_new, mom_new


@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision: weight is bf16/fp16, master copy weight32 is fp32."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@register("nag_mom_update", num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w, mean_new, var_new


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_grad, wd)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g_st, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    g_new = (1 - gamma1) * g + gamma1 * g_st
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_new, delta_new


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) > lamda1,
        -(z_new - jnp.sign(z_new) * lamda1) / ((beta + jnp.sqrt(n_new)) / lr + wd),
        jnp.zeros_like(weight))
    return w, z_new, n_new


@register("adagrad_update", num_outputs=2, aliases=("_sparse_adagrad_update",))
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    hist_new = history + jnp.square(g)
    return weight - lr * (g / (jnp.sqrt(hist_new) + epsilon) + wd * weight), hist_new


@register("adadelta_update", num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """Reference AdaDelta is a Python optimizer (optimizer.py:1802-1824):
    clip rescale*grad WITHOUT wd, then weight -= delta + wd*weight."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    acc_g_new = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g_new + epsilon) * g
    acc_delta_new = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta - wd * weight, acc_g_new, acc_delta_new


@register("lars_sgd_update")
def lars_sgd_update(weight, grad, lr=0.01, eta=0.001, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """LARS scaling + SGD in ONE executable — norms computed on device
    (parity: optimizer.py LARS, without the reference's host round trip)."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    lars_lr = jnp.where((w_norm > 0) & (g_norm > 0),
                        lr * eta * w_norm / (g_norm + wd * w_norm + epsilon),
                        lr)
    return weight - lars_lr * (g + wd * weight)


@register("lars_sgd_mom_update", num_outputs=2)
def lars_sgd_mom_update(weight, grad, mom, lr=0.01, eta=0.001, epsilon=1e-8,
                        momentum=0.0, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    lars_lr = jnp.where((w_norm > 0) & (g_norm > 0),
                        lr * eta * w_norm / (g_norm + wd * w_norm + epsilon),
                        lr)
    mom_new = momentum * mom - lars_lr * (g + wd * weight)
    return weight + mom_new, mom_new


@register("lamb_update_phase1", num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = mean_new, var_new
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight, mean_new, var_new


@register("lamb_update_phase2")
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                      jnp.ones_like(r1))
    return weight - lr * ratio * g_update
