"""Optimizer update steps as fused ops.

Parity target: `src/operator/optimizer_op.cc:49-970` — the reference makes
every optimizer update a *registered op* (sgd_update, sgd_mom_update,
mp_sgd_update (multi-precision), adam_update, ftml, lamb_update_phase1/2, …)
so updates run fused on-device without Python in the loop.

TPU-native: each update is a pure function over (weight, grad, states...)
returning the new tensors; the registry jits one executable per
(op, hyper-params) pair, and `multi_*` aggregated variants are realised by
the Trainer jitting a single update over the whole parameter pytree (better
than the reference's fixed-size multi-tensor kernels — XLA fuses across the
whole step).

All updates honour rescale_grad / clip_gradient / wd exactly as the
reference kernels do.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd):
    """Adam-family gradient prep: fold wd*weight in BEFORE clipping.

    The reference's AdamUpdateKernel (and ftml/rmsprop/rmspropalex,
    optimizer_op-inl.h:1215,1303,1966,2064) computes
    grad = rescale*grad + wd*weight and clips the sum; the SGD-family
    kernels clip first. Preserving the ordering keeps numerics identical
    whenever clip_gradient is set with nonzero wd.
    """
    g = grad * rescale_grad + wd * weight
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight)
    return weight + mom_new, mom_new


@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision: weight is bf16/fp16, master copy weight32 is fp32."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@register("nag_mom_update", num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w, mean_new, var_new


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_grad, wd)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g_st, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad_wd(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    g_new = (1 - gamma1) * g + gamma1 * g_st
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_new, delta_new


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) > lamda1,
        -(z_new - jnp.sign(z_new) * lamda1) / ((beta + jnp.sqrt(n_new)) / lr + wd),
        jnp.zeros_like(weight))
    return w, z_new, n_new


@register("adagrad_update", num_outputs=2, aliases=("_sparse_adagrad_update",))
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    hist_new = history + jnp.square(g)
    return weight - lr * (g / (jnp.sqrt(hist_new) + epsilon) + wd * weight), hist_new


@register("adadelta_update", num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """Reference AdaDelta is a Python optimizer (optimizer.py:1802-1824):
    clip rescale*grad WITHOUT wd, then weight -= delta + wd*weight."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    acc_g_new = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g_new + epsilon) * g
    acc_delta_new = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta - wd * weight, acc_g_new, acc_delta_new


@register("lars_sgd_update")
def lars_sgd_update(weight, grad, lr=0.01, eta=0.001, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """LARS scaling + SGD in ONE executable — norms computed on device
    (parity: optimizer.py LARS, without the reference's host round trip)."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    lars_lr = jnp.where((w_norm > 0) & (g_norm > 0),
                        lr * eta * w_norm / (g_norm + wd * w_norm + epsilon),
                        lr)
    return weight - lars_lr * (g + wd * weight)


@register("lars_sgd_mom_update", num_outputs=2)
def lars_sgd_mom_update(weight, grad, mom, lr=0.01, eta=0.001, epsilon=1e-8,
                        momentum=0.0, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    lars_lr = jnp.where((w_norm > 0) & (g_norm > 0),
                        lr * eta * w_norm / (g_norm + wd * w_norm + epsilon),
                        lr)
    mom_new = momentum * mom - lars_lr * (g + wd * weight)
    return weight + mom_new, mom_new


@register("lamb_update_phase1", num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = mean_new, var_new
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight, mean_new, var_new


@register("lamb_update_phase2")
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                      jnp.ones_like(r1))
    return weight - lr * ratio * g_update


# ------------------------------------------------------------- adamw -------

def _adamw_core(weight32, g, mean, var, lr, beta1, beta2, epsilon, wd, eta,
                rescale):
    """Shared AdamW math (parity: src/operator/contrib/adamw.cc — decoupled
    weight decay, NO bias correction, whole update skipped when the dynamic
    rescale_grad tensor is non-finite — the loss-scaler contract)."""
    ok = jnp.isfinite(rescale) & jnp.all(jnp.isfinite(g))
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    step = lr * (mean_new / (jnp.sqrt(var_new) + epsilon) + wd * weight32)
    w_new = weight32 - eta * step
    return (jnp.where(ok, w_new, weight32), jnp.where(ok, mean_new, mean),
            jnp.where(ok, var_new, var))


@register("_adamw_update", num_outputs=3, aliases=("adamw_update",))
def adamw_update(weight, grad, mean, var, rescale_grad, lr=0.001, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                 clip_gradient=-1.0):
    """parity: contrib/adamw.cc _adamw_update — rescale_grad is a TENSOR
    input (1/loss_scale from the AMP scaler); non-finite skips the step."""
    rescale = jnp.reshape(rescale_grad, ())
    g = grad * rescale
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return _adamw_core(weight, g, mean, var, lr, beta1, beta2, epsilon, wd,
                       eta, rescale)


@register("_mp_adamw_update", num_outputs=4, aliases=("mp_adamw_update",))
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad,
                    lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    eta=1.0, clip_gradient=-1.0):
    rescale = jnp.reshape(rescale_grad, ())
    g = grad.astype(jnp.float32) * rescale
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w32, m, v = _adamw_core(weight32, g, mean, var, lr, beta1, beta2,
                            epsilon, wd, eta, rescale)
    return w32.astype(weight.dtype), m, v, w32


@register("mp_nag_mom_update", num_outputs=3)
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """parity: optimizer_op.cc mp_nag_mom_update — NAG on the fp32 master."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient) \
        + wd * weight32
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return w32.astype(weight.dtype), mom_new, w32


@register("mp_lamb_update_phase1", num_outputs=3)
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = mean_new, var_new
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight32, mean_new, var_new


@register("mp_lamb_update_phase2", num_outputs=2)
def mp_lamb_update_phase2(weight, g_update, r1, r2, weight32, lr=0.01,
                          lower_bound=-1.0, upper_bound=-1.0):
    w32 = lamb_update_phase2(weight32, g_update, r1, r2, lr=lr,
                             lower_bound=lower_bound, upper_bound=upper_bound)
    return w32.astype(weight.dtype), w32


# ---------------------------------------------- multi-tensor variants ------
# The reference ships fixed-arity fused kernels (optimizer_op.cc
# MultiSGDUpdate, preloaded_multi_*, contrib multi_lamb/multi_adamw). Here
# each is one jitted executable over the whole interleaved tensor list —
# XLA fuses across parameters, which is the same batching the kernels
# hand-roll. Functional convention: outputs are all updated tensors
# (weights first, then state tensors per weight).

def _multi_n(kw):
    # multi_lamb ops use the reference's `num_tensors` name; the sgd/adamw
    # families use `num_weights` — accept either so symbolic output counts
    # always match the executed tuple
    return int(kw.get("num_weights") or kw.get("num_tensors") or 1)


@register("multi_sgd_update", num_outputs=lambda n, kw: _multi_n(kw))
def multi_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    """args = [w0, g0, w1, g1, ...] (parity: optimizer_op.cc:473)."""
    outs = []
    for i in range(num_weights):
        w, g = args[2 * i], args[2 * i + 1]
        outs.append(sgd_update.fn(w, g, lr=lrs[i], wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", num_outputs=lambda n, kw: 2 * _multi_n(kw))
def multi_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    """args = [w0, g0, mom0, ...]; returns weights then momenta."""
    ws, moms = [], []
    for i in range(num_weights):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        w2, m2 = sgd_mom_update.fn(w, g, m, lr=lrs[i], momentum=momentum,
                                   wd=wds[i], rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient)
        ws.append(w2)
        moms.append(m2)
    return tuple(ws + moms)


@register("multi_mp_sgd_update", num_outputs=lambda n, kw: 2 * _multi_n(kw))
def multi_mp_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1):
    """args = [w0, g0, w32_0, ...]; returns weights then fp32 masters."""
    ws, w32s = [], []
    for i in range(num_weights):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        w2, w32_2 = mp_sgd_update.fn(w, g, w32, lr=lrs[i], wd=wds[i],
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)
        ws.append(w2)
        w32s.append(w32_2)
    return tuple(ws + w32s)


@register("multi_mp_sgd_mom_update",
          num_outputs=lambda n, kw: 3 * _multi_n(kw))
def multi_mp_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1):
    """args = [w0, g0, mom0, w32_0, ...]."""
    ws, moms, w32s = [], [], []
    for i in range(num_weights):
        w, g, m, w32 = args[4 * i:4 * i + 4]
        w2, m2, w32_2 = mp_sgd_mom_update.fn(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(w2)
        moms.append(m2)
        w32s.append(w32_2)
    return tuple(ws + moms + w32s)


@register("preloaded_multi_sgd_update",
          num_outputs=lambda n, kw: _multi_n(kw))
def preloaded_multi_sgd_update(*args, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=1):
    """args = [w0, g0, ..., lrs, wds] — lr/wd arrive as device tensors so
    schedules never leave the device (parity: preloaded_multi_sgd_*)."""
    lrs, wds = args[-2], args[-1]
    outs = []
    for i in range(num_weights):
        w, g = args[2 * i], args[2 * i + 1]
        outs.append(sgd_update.fn(w, g, lr=lrs[i], wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient))
    return tuple(outs)


@register("preloaded_multi_sgd_mom_update",
          num_outputs=lambda n, kw: 2 * _multi_n(kw))
def preloaded_multi_sgd_mom_update(*args, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1):
    lrs, wds = args[-2], args[-1]
    ws, moms = [], []
    for i in range(num_weights):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        w2, m2 = sgd_mom_update.fn(w, g, m, lr=lrs[i], momentum=momentum,
                                   wd=wds[i], rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient)
        ws.append(w2)
        moms.append(m2)
    return tuple(ws + moms)


@register("preloaded_multi_mp_sgd_update",
          num_outputs=lambda n, kw: 2 * _multi_n(kw))
def preloaded_multi_mp_sgd_update(*args, rescale_grad=1.0,
                                  clip_gradient=-1.0, num_weights=1):
    lrs, wds = args[-2], args[-1]
    ws, w32s = [], []
    for i in range(num_weights):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        w2, w32_2 = mp_sgd_update.fn(w, g, w32, lr=lrs[i], wd=wds[i],
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)
        ws.append(w2)
        w32s.append(w32_2)
    return tuple(ws + w32s)


@register("preloaded_multi_mp_sgd_mom_update",
          num_outputs=lambda n, kw: 3 * _multi_n(kw))
def preloaded_multi_mp_sgd_mom_update(*args, momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0, num_weights=1):
    lrs, wds = args[-2], args[-1]
    ws, moms, w32s = [], [], []
    for i in range(num_weights):
        w, g, m, w32 = args[4 * i:4 * i + 4]
        w2, m2, w32_2 = mp_sgd_mom_update.fn(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(w2)
        moms.append(m2)
        w32s.append(w32_2)
    return tuple(ws + moms + w32s)


@register("multi_lars")
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """parity: contrib/multi_lars.cc — layerwise LARS coefficients for a
    whole parameter set in one op (inputs are the per-layer norms computed
    by multi_sum_sq)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    coef = eta * w_norm / (g_norm + wds * w_norm + eps)
    return lrs * jnp.where((w_norm > 0) & (g_norm > 0), coef,
                           jnp.ones_like(coef))


@register("_contrib_group_adagrad_update", num_outputs=2,
          aliases=("group_adagrad_update",))
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """parity: contrib/optimizer_op.cc GroupAdagrad — one accumulator per
    row (embedding-friendly Adagrad)."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    axes = tuple(range(1, g.ndim))
    hist_new = history + jnp.mean(jnp.square(g), axis=axes, keepdims=True) \
        if axes else history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(hist_new) + epsilon), hist_new


@register("all_finite", differentiable=False)
def all_finite(data, init_output=True):
    """parity: contrib/all_finite.cc — scalar 1.0 iff every element is
    finite (the AMP loss-scaler probe)."""
    return jnp.all(jnp.isfinite(data)).astype(jnp.float32)


@register("amp_multicast", num_outputs=lambda n, kw:
          int(kw.get("num_outputs", n or 1)))
def amp_multicast(*args, num_outputs=None, cast_narrow=False):
    """parity: tensor/amp_cast.cc AMPMultiCast — cast every input to the
    widest (or narrowest, cast_narrow=True) dtype among them."""
    dtypes = [a.dtype for a in args]
    target = dtypes[0]
    order = {jnp.dtype(jnp.float16): 0, jnp.dtype(jnp.bfloat16): 0,
             jnp.dtype(jnp.float32): 1, jnp.dtype(jnp.float64): 2}
    for dt in dtypes[1:]:
        a, b = order.get(jnp.dtype(dt), 1), order.get(jnp.dtype(target), 1)
        if (a < b) if cast_narrow else (a > b):
            target = dt
    return tuple(a.astype(target) for a in args)


@register("reset_arrays", num_outputs=lambda n, kw:
          int(kw.get("num_arrays", n or 1)), differentiable=False)
def reset_arrays(*args, num_arrays=1):
    """parity: contrib/reset_arrays.cc — zero every input (functional:
    returns zeroed tensors; callers rebind)."""
    return tuple(jnp.zeros_like(a) for a in args)


@register("_multi_adamw_update",
          num_outputs=lambda n, kw: 3 * _multi_n(kw),
          aliases=("multi_adamw_update",))
def multi_adamw_update(*args, lrs=(), wds=(), etas=(), beta1=0.9,
                       beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                       num_weights=1):
    """args = [w0, g0, mean0, var0, ...] + [rescale_grad] (tensor).
    parity: contrib/adamw.cc multi_adamw_update."""
    rescale = jnp.reshape(args[-1], ())
    ws, ms, vs = [], [], []
    for i in range(num_weights):
        w, g, m, v = args[4 * i:4 * i + 4]
        gg = g * rescale
        if clip_gradient is not None and clip_gradient > 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        w2, m2, v2 = _adamw_core(w, gg, m, v, lrs[i], beta1, beta2,
                                 epsilon, wds[i], etas[i], rescale)
        ws.append(w2)
        ms.append(m2)
        vs.append(v2)
    return tuple(ws + ms + vs)


@register("_multi_mp_adamw_update",
          num_outputs=lambda n, kw: 4 * _multi_n(kw),
          aliases=("multi_mp_adamw_update",))
def multi_mp_adamw_update(*args, lrs=(), wds=(), etas=(), beta1=0.9,
                          beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                          num_weights=1):
    """args = [w0, g0, mean0, var0, w32_0, ...] + [rescale_grad]."""
    rescale = jnp.reshape(args[-1], ())
    ws, ms, vs, w32s = [], [], [], []
    for i in range(num_weights):
        w, g, m, v, w32 = args[5 * i:5 * i + 5]
        gg = g.astype(jnp.float32) * rescale
        if clip_gradient is not None and clip_gradient > 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        w32_2, m2, v2 = _adamw_core(w32, gg, m, v, lrs[i], beta1, beta2,
                                    epsilon, wds[i], etas[i], rescale)
        ws.append(w32_2.astype(w.dtype))
        ms.append(m2)
        vs.append(v2)
        w32s.append(w32_2)
    return tuple(ws + ms + vs + w32s)


@register("_multi_lamb_update",
          num_outputs=lambda n, kw: 3 * _multi_n(kw),
          aliases=("multi_lamb_update",))
def multi_lamb_update(*args, learning_rates=(), wds=(), beta1=0.9,
                      beta2=0.999, epsilon=1e-6, step_count=(),
                      bias_correction=True, rescale_grad=1.0,
                      lower_bound=-1.0, upper_bound=-1.0,
                      clip_gradient=-1.0, num_tensors=1, num_weights=None):
    """args = [w0, g0, mean0, var0, ...]; parity: contrib/multi_lamb.cc —
    full LAMB (phase1+trust ratio+phase2) per tensor in one executable."""
    n = num_weights or num_tensors
    ws, ms, vs = [], [], []
    for i in range(n):
        w, g, m, v = args[4 * i:4 * i + 4]
        t = step_count[i] if step_count else 1
        upd, m2, v2 = lamb_update_phase1.fn(
            w, g, m, v, beta1=beta1, beta2=beta2, epsilon=epsilon, t=t,
            bias_correction=bias_correction, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        r1 = jnp.sqrt(jnp.sum(jnp.square(w)))
        r2 = jnp.sqrt(jnp.sum(jnp.square(upd)))
        w2 = lamb_update_phase2.fn(w, upd, r1, r2, lr=learning_rates[i],
                                   lower_bound=lower_bound,
                                   upper_bound=upper_bound)
        ws.append(w2)
        ms.append(m2)
        vs.append(v2)
    return tuple(ws + ms + vs)


@register("_multi_mp_lamb_update",
          num_outputs=lambda n, kw: 4 * _multi_n(kw),
          aliases=("multi_mp_lamb_update",))
def multi_mp_lamb_update(*args, learning_rates=(), wds=(), beta1=0.9,
                         beta2=0.999, epsilon=1e-6, step_count=(),
                         bias_correction=True, rescale_grad=1.0,
                         lower_bound=-1.0, upper_bound=-1.0,
                         clip_gradient=-1.0, num_tensors=1,
                         num_weights=None):
    """args = [w0, g0, mean0, var0, w32_0, ...]."""
    n = num_weights or num_tensors
    ws, ms, vs, w32s = [], [], [], []
    for i in range(n):
        w, g, m, v, w32 = args[5 * i:5 * i + 5]
        t = step_count[i] if step_count else 1
        upd, m2, v2 = lamb_update_phase1.fn(
            w32, g.astype(jnp.float32), m, v, beta1=beta1, beta2=beta2,
            epsilon=epsilon, t=t, bias_correction=bias_correction,
            wd=wds[i], rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        r1 = jnp.sqrt(jnp.sum(jnp.square(w32)))
        r2 = jnp.sqrt(jnp.sum(jnp.square(upd)))
        w32_2 = lamb_update_phase2.fn(w32, upd, r1, r2,
                                      lr=learning_rates[i],
                                      lower_bound=lower_bound,
                                      upper_bound=upper_bound)
        ws.append(w32_2.astype(w.dtype))
        ms.append(m2)
        vs.append(v2)
        w32s.append(w32_2)
    return tuple(ws + ms + vs + w32s)
