"""Tensor structure ops: reductions, linalg, indexing, shape manipulation.

Parity target: `src/operator/tensor/` in the reference — reduce
(`broadcast_reduce_op.h`), `dot` (`dot-inl.h`), indexing
(`indexing_op.cc`: take/gather_nd/scatter_nd/Embedding/one_hot), matrix ops
(`matrix_op.cc`: transpose/reshape/slice/concat/...), ordering
(`ordering_op.cc`: sort/argsort/topk), init ops (`init_op.cc`).

TPU-native notes: `dot`/`batch_dot` lower straight onto the MXU via
`lax.dot_general` with a bf16-friendly `preferred_element_type`; gathers and
scatters use XLA's native gather/scatter (no hand-written kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register


def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ----------------------------------------------------------- reductions ----

def _make_reduce(jfn):
    def red(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            all_ax = set(range(x.ndim))
            keep = {a % x.ndim for a in (ax if isinstance(ax, tuple) else (ax,))}
            ax = tuple(sorted(all_ax - keep))
        return jfn(x, axis=ax, keepdims=keepdims)

    return red


for _name, _jfn in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                    ("nansum", jnp.nansum), ("nanprod", jnp.nanprod),
                    ("max", jnp.max), ("min", jnp.min)]:
    register(_name, aliases=(f"_np_{_name}",))(_make_reduce(_jfn))


@register("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=_norm_axis(axis), keepdims=keepdims)
    return out.astype(jnp.float32)  # parity: MXNet argmax returns float


@register("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=_norm_axis(axis), keepdims=keepdims).astype(jnp.float32)


@register("argsort", differentiable=False)
def _argsort(x, axis=-1, is_ascend=True):
    idx = jnp.argsort(x, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.float32)


@register("sort")
def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("topk", differentiable=False)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import canonical_dtype

    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(canonical_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    return idx


# --------------------------------------------------------------- linalg ----

@register("dot")
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2) if lhs.ndim > 1 else lhs
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2) if rhs.ndim > 1 else rhs
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    # MXNet dot: contract last axis of lhs with first axis of rhs
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot")
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def _potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_syrk")
def _syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("khatri_rao")
def _khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


# ------------------------------------------------------------- indexing ----

@register("take")
def _take(a, indices, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@register("pick")
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register("gather_nd")
def _gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=()):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].add(data)


@register("Embedding")
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
               sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("one_hot")
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import canonical_dtype

    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(canonical_dtype(dtype))


@register("where")
def _where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("boolean_mask", differentiable=False, eager=True)
def _boolean_mask(data, index, axis=0):
    # dynamic-shape op: output size depends on the mask VALUES, so it
    # must run eagerly, never under jit (parity: test_dynamic_shape.py);
    # inside traces use `where`.
    return jnp.compress(_np.asarray(index).astype(bool), data, axis=axis)


# -------------------------------------------------------- shape manip ------

@register("reshape", aliases=("Reshape",))
def _reshape(x, shape=()):
    # Supports MXNet special codes 0 (copy dim) and -1 (infer)
    tgt = []
    for i, s in enumerate(shape):
        if s == 0:
            tgt.append(x.shape[i])
        elif s == -2:
            tgt.extend(x.shape[i:])
        else:
            tgt.append(int(s))
    return jnp.reshape(x, tuple(tgt))


@register("reshape_like")
def _reshape_like(x, like):
    return jnp.reshape(x, like.shape)


@register("shape_array", differentiable=False)
def _shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def _size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


@register("transpose")
def _transpose(x, axes=()):
    return jnp.transpose(x, tuple(axes) if axes else None)


@register("expand_dims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis=_norm_axis(axis))


@register("Flatten", aliases=("flatten",))
def _flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("Concat", aliases=("concat",))
def _concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def _stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


def _split_impl(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


register("SliceChannel", aliases=("split", "slice_channel"),
         num_outputs=lambda n_in, kw: int(kw.get("num_outputs", 1)))(_split_impl)


@register("slice")
def _slice(x, begin=(), end=(), step=()):
    slices = []
    for i in range(x.ndim):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if step and i < len(step) and step[i] else None
        slices.append(slice(b, e, s))
    return x[tuple(slices)]


@register("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    sl = [slice(None)] * x.ndim
    sl[axis] = slice(begin, end)
    return x[tuple(sl)]


@register("slice_like")
def _slice_like(x, like, axes=()):
    axes = tuple(axes) if axes else tuple(range(x.ndim))
    sl = [slice(None)] * x.ndim
    for a in axes:
        sl[a] = slice(0, like.shape[a])
    return x[tuple(sl)]


@register("flip", aliases=("reverse",))
def _flip(x, axis=0):
    return jnp.flip(x, axis=_norm_axis(axis))


@register("tile")
def _tile(x, reps=()):
    return jnp.tile(x, tuple(reps))


@register("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


@register("swapaxes", aliases=("SwapAxis",))
def _swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("depth_to_space")
def _depth_to_space(x, block_size=1):
    b, c, h, w = x.shape
    bs = block_size
    x = x.reshape(b, bs, bs, c // (bs * bs), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(b, c // (bs * bs), h * bs, w * bs)


@register("space_to_depth")
def _space_to_depth(x, block_size=1):
    b, c, h, w = x.shape
    bs = block_size
    x = x.reshape(b, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(b, c * bs * bs, h // bs, w // bs)


# -------------------------------------------------------------- sequence ---

@register("SequenceMask", aliases=("sequence_mask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                   axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :].astype(steps.dtype)  # (T, B)
    if axis == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast", aliases=("sequence_last",))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = data.shape[axis] - 1
        return jnp.take(data, idx, axis=axis)
    last = (sequence_length.astype(jnp.int32) - 1)  # (B,)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0
    )[0]


@register("SequenceReverse", aliases=("sequence_reverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)
    T = moved.shape[0]
    steps = jnp.arange(T)[:, None]
    slen = sequence_length.astype(jnp.int32)[None, :]
    idx = jnp.where(steps < slen, slen - 1 - steps, steps)
    out = jnp.take_along_axis(moved, idx.reshape(idx.shape + (1,) * (moved.ndim - 2)),
                              axis=0)
    return jnp.moveaxis(out, 0, axis)


# ------------------------------------------------------- legacy tail ops ---

@register("batch_take")
def _batch_take(a, indices):
    """parity: src/operator/tensor/indexing_op.cc batch_take — pick one
    element per row."""
    return a[jnp.arange(a.shape[0]), indices.astype(jnp.int32)]


@register("diag")
def _diag(data, k=0, axis1=0, axis2=1):
    """parity: src/operator/tensor/diag_op.cc."""
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


def _split_v2_indices(indices):
    """The reference python wrapper stores ``[0] + indices`` in the op attr
    (ndarray.py split_v2); accept both that convention (reference-produced
    symbol.json) and bare user indices."""
    idx = list(indices)
    if idx and idx[0] == 0:
        idx = idx[1:]
    return idx


@register("split_v2", num_outputs=lambda n_in, kw:
          int(kw["sections"]) if kw.get("sections")
          else len(_split_v2_indices(kw.get("indices", ()))) + 1)
def _split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    """parity: matrix_op.cc split_v2 — split at explicit indices or into
    equal sections."""
    if sections:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, _split_v2_indices(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("digamma")
def _digamma(data):
    return jax.scipy.special.digamma(data)


@register("multi_sum_sq")
def _multi_sum_sq(*arrays, num_arrays=1):
    """parity: contrib/multi_sum_sq.cc — ONE output vector holding each
    array's sum of squares (used by LANS/LAMB aggregated updates)."""
    return jnp.stack([jnp.sum(jnp.square(a)) for a in arrays])


@register("unravel_index")
def _unravel_index(data, shape=()):
    """parity: tensor/ravel.cc — flat index -> coordinates (ndim, N)."""
    coords = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(coords, axis=0)


@register("ravel_multi_index")
def _ravel_multi_index(data, shape=()):
    """parity: tensor/ravel.cc — coordinates (ndim, N) -> flat index."""
    return jnp.ravel_multi_index(
        tuple(data[i].astype(jnp.int32) for i in range(data.shape[0])),
        tuple(shape), mode="clip")


@register("choose_element_0index")
def _choose_element_0index(lhs, rhs):
    """parity: legacy choose_element_0index == batch_take."""
    return lhs[jnp.arange(lhs.shape[0]), rhs.astype(jnp.int32)]


@register("fill_element_0index")
def _fill_element_0index(lhs, mhs, rhs):
    """parity: legacy fill_element_0index — set lhs[i, rhs[i]] = mhs[i]."""
    return lhs.at[jnp.arange(lhs.shape[0]), rhs.astype(jnp.int32)].set(mhs)


@register("argmax_channel", differentiable=False)
def _argmax_channel(data):
    """parity: broadcast_reduce_op_index.cc argmax_channel."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ------------------------------------------------------ legacy tail 2 ------

@register("add_n", aliases=("ElementWiseSum", "_sum"))
def _add_n(*args, num_args=None):
    """parity: tensor/elemwise_sum.cc — sum of N tensors in one op."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("moments", num_outputs=2)
def _moments(data, axes=(), keepdims=False):
    """parity: nn/moments.cc — (mean, variance) over `axes` in one pass."""
    ax = tuple(axes) if axes else None
    mean = jnp.mean(data, axis=ax, keepdims=keepdims)
    mk = mean if keepdims or ax is None else jnp.expand_dims(mean, ax)
    var = jnp.mean(jnp.square(data - jnp.reshape(mk, mk.shape)), axis=ax,
                   keepdims=keepdims)
    return mean, var


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    """parity: loss_binary_op.cc softmax_cross_entropy — summed CE of
    softmax(data) against integer labels."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[..., None], axis=-1)
    return -jnp.sum(picked)


@register("_histogram", num_outputs=2, differentiable=False,
          aliases=("histogram",))
def _histogram_op(data, bins=None, bin_cnt=10, range=None):
    """parity: tensor/histogram.cc — counts + bin edges. `bins` may be an
    explicit edge tensor (second input in the reference); otherwise
    `bin_cnt` uniform bins over `range` (defaults to data min/max)."""
    if bins is not None:
        edges = bins
        hist = jnp.histogram(data.reshape(-1), bins=edges)[0]
        return hist, edges
    lo, hi = (range if range is not None
              else (jnp.min(data), jnp.max(data)))
    hist, edges = jnp.histogram(data.reshape(-1), bins=int(bin_cnt),
                                range=(lo, hi))
    return hist, edges


@register("col2im")
def _col2im(data, output_size=(), kernel=(), stride=(1, 1), dilate=(1, 1),
            pad=(0, 0)):
    """parity: nn/im2col.cc col2im — fold sliding-window columns back into
    the image by summing overlaps (the transpose of im2col)."""
    n, ckk, l = data.shape
    kh, kw = kernel
    c = ckk // (kh * kw)
    oh, ow = output_size
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    hpad, wpad = oh + 2 * ph, ow + 2 * pw
    out_h = (hpad - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (wpad - (dw * (kw - 1) + 1)) // sw + 1
    cols = data.reshape(n, c, kh, kw, out_h, out_w)
    img = jnp.zeros((n, c, hpad, wpad), data.dtype)
    for i in range(kh):
        for j in range(kw):
            img = img.at[:, :, i * dh:i * dh + sh * out_h:sh,
                         j * dw:j * dw + sw * out_w:sw].add(
                cols[:, :, i, j])
    return img[:, :, ph:ph + oh, pw:pw + ow]


@register("_slice_assign")
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """parity: matrix_op.cc _slice_assign — functional slice write (the
    NDArray setitem fast path)."""
    idx = tuple(slice(b if b is not None else None,
                      e if e is not None else None,
                      s if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return lhs.at[idx].set(rhs)


@register("_slice_assign_scalar")
def _slice_assign_scalar(lhs, scalar=0.0, begin=(), end=(), step=()):
    idx = tuple(slice(b if b is not None else None,
                      e if e is not None else None,
                      s if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return lhs.at[idx].set(scalar)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices):
    """parity: indexing_op.cc _scatter_set_nd — advanced-index write."""
    return lhs.at[tuple(indices.astype(jnp.int32))].set(rhs)


@register("_rnn_param_concat")
def _rnn_param_concat(*args, dim=0, num_args=None):
    """parity: rnn.cc _rnn_param_concat — flat fused-parameter pack."""
    return jnp.concatenate([a.reshape(-1) if dim == 0 else a for a in args],
                           axis=0 if dim == 0 else dim)


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """parity: elemwise_unary_op_basic.cc — identity of lhs, shape/stype
    attrs borrowed from rhs during inference (shapes already agree here)."""
    return lhs


@register("cast_storage", eager=True)
def _cast_storage(data, stype="default"):
    """parity: tensor/cast_storage.cc. Dense XLA buffers back every
    storage type here (ndarray/sparse.py wraps them in the row_sparse/csr
    view classes at the NDArray layer); the op is the dense identity."""
    return data


# legacy internal creation-op names (init_op.cc registrations; the public
# nd.zeros/ones/arange route here too)

@register("_zeros", differentiable=False,
          aliases=("_zeros_without_dtype",))
def _zeros_op(shape=(), dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.zeros(tuple(shape), canonical_dtype(dtype or "float32"))


@register("_ones", differentiable=False)
def _ones_op(shape=(), dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.ones(tuple(shape), canonical_dtype(dtype or "float32"))


@register("_full", differentiable=False)
def _full_op(shape=(), value=0.0, dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.full(tuple(shape), value, canonical_dtype(dtype or "float32"))


@register("_arange", differentiable=False)
def _arange_op(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
               dtype="float32", ctx=None):
    from ..base import canonical_dtype

    out = jnp.arange(start, stop, step, canonical_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", differentiable=False)
def _linspace_op(start=0.0, stop=1.0, num=50, endpoint=True,
                 dtype="float32", ctx=None):
    from ..base import canonical_dtype

    return jnp.linspace(start, stop, int(num),
                        endpoint=endpoint).astype(canonical_dtype(dtype))


@register("_sparse_retain")
def _sparse_retain_op(data, indices):
    """parity: sparse_retain.cc — keep only the listed rows (dense
    emitter; the NDArray layer keeps row_sparse structure)."""
    mask = jnp.zeros((data.shape[0],), bool).at[
        indices.astype(jnp.int32)].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data,
                     jnp.zeros_like(data))
