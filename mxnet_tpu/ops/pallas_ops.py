"""Pallas-backed attention ops — the op-registration shim over the
kernel layer.

The flash attention kernel itself lives in
``mxnet_tpu/kernels/flash.py`` (PR 16 moved it into the kernel
registry); this module keeps the *op* surface — ``_contrib_flash_
attention`` and the serving-decode ``_contrib_decode_attention`` — and
routes through :func:`mxnet_tpu.kernels.dispatch`, which picks kernel
vs dense-XLA per (backend, shape bucket) from the autotuned dispatch
table and LATCHES the Pallas-unavailable fallback (one ``log.warning``
+ ``mxtpu_kernels_fallback_total{family}`` per process, never a silent
per-call re-probe — the old behavior here was exactly that bug).

parity role: contrib transformer attention + the long-context machinery
of SURVEY §5.7 (composes with parallel/ring_attention for the sharded
case: ring over devices, flash within a device).
"""
from __future__ import annotations

from .registry import register

# Re-exported for callers and tests that treat this module as the home
# of the attention numerics (tests/test_pallas.py imports both).
from ..kernels.flash import flash_attention_reference  # noqa: F401
from ..kernels.flash import flash_forward as _flash_forward  # noqa: F401, unused-import
from ..kernels.decode_attention import decode_attention_reference  # noqa: F401

__all__ = ["flash_attention_reference", "decode_attention_reference"]


@register("_contrib_flash_attention")
def _contrib_flash_attention(q, k, v, scale=None, causal=False,
                             block_q=128, block_k=128, interpret=False):
    """Fused attention over (B, H, S, D) tensors.

    Dispatches to the Pallas flash kernel (registry family
    ``flash_attention``) when the shape passes the statically checkable
    Mosaic constraints AND the dispatch table (or the on-TPU default)
    picks it; dense XLA softmax otherwise. `interpret=True` forces the
    kernel through the Pallas interpreter (CPU CI). Training memory
    stays O(S*block): the backward is the blocked flash recurrence, not
    a dense recompute."""
    if q.ndim != 4:
        raise ValueError(
            f"flash_attention expects (B, H, S, D) inputs, got rank "
            f"{q.ndim}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    from .. import kernels as _kernels

    return _kernels.dispatch(
        "flash_attention", q, k, v, float(scale), causal=bool(causal),
        block_q=int(block_q), block_k=int(block_k),
        interpret=bool(interpret) or None)


@register("_contrib_decode_attention")
def _contrib_decode_attention(q, k, v, lengths, scale=None, block_k=128,
                              interpret=False):
    """Single-query decode attention: ``q (B, H, D)`` against a padded
    KV cache ``k/v (B, H, S, D)`` with per-sequence valid ``lengths
    (B,)`` (each >= 1). Registry family ``decode_attention`` — the
    Pallas kernel skips fully-padded cache blocks so decode cost tracks
    the filled cache; dense masked softmax otherwise."""
    if q.ndim != 3 or k.ndim != 4:
        raise ValueError(
            f"decode_attention expects q (B, H, D) and k/v (B, H, S, D),"
            f" got ranks {q.ndim}/{k.ndim}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    from .. import kernels as _kernels

    return _kernels.dispatch(
        "decode_attention", q, k, v, lengths, float(scale),
        block_k=int(block_k), interpret=bool(interpret) or None)
