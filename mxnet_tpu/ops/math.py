"""Elementwise / scalar / broadcast / logic ops.

Parity target: `src/operator/tensor/elemwise_*.{h,cc,cu}` and
`src/operator/mshadow_op.h` in the reference (~36k LoC of templated CPU/GPU
kernels + registration macros `tensor/elemwise_unary_op.h:810-873`).

TPU-native: each op is one jax.numpy/lax expression; XLA fuses chains of
these into single kernels (replacing the reference's NVRTC pointwise-fusion
pass, `src/executor/pointwise_fusion_pass.cc`). Binary `elemwise_*` ops
require identical shapes (as in the reference); `broadcast_*` ops use numpy
broadcasting. Scalar variants bake the scalar into the executable just like
the reference's `_plus_scalar(scalar=...)` parameterised kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

# ---------------------------------------------------------------- unary ----

_UNARY = {
    "negative": jnp.negative,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "cbrt": jnp.cbrt,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "gamma": lambda x: jnp.exp(jax.lax.lgamma(x)),
    "gammaln": jax.lax.lgamma,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "reciprocal": jnp.reciprocal,
    "logical_not": jnp.logical_not,
}

for _name, _fn in _UNARY.items():
    register(_name)(_fn)

register("copy", aliases=("identity", "_copy"))(lambda x: jnp.asarray(x))
register("zeros_like")(jnp.zeros_like)
register("ones_like")(jnp.ones_like)
register("LeakyReLU")(
    lambda x, act_type="leaky", slope=0.25: {
        "leaky": lambda: jnp.where(x >= 0, x, slope * x),
        "elu": lambda: jnp.where(x >= 0, x, slope * jnp.expm1(x)),
        "selu": lambda: 1.0507009873554805 * jnp.where(
            x >= 0, x, 1.6732632423543772 * jnp.expm1(x)),
        "gelu": lambda: jax.nn.gelu(x, approximate=False),
    }[act_type]()
)
register("hard_sigmoid")(lambda x, alpha=0.2, beta=0.5: jnp.clip(alpha * x + beta, 0, 1))
register("softplus")(jax.nn.softplus)
register("degrees")(jnp.degrees)
register("radians")(jnp.radians)


@register("clip")
def _clip(x, a_min=None, a_max=None):
    """Clamp every element into [a_min, a_max] (parity: clip,
    matrix_op.cc)."""
    return jnp.clip(x, a_min, a_max)


@register("Cast", aliases=("cast",))
def _cast(x, dtype="float32"):
    """Cast to the given dtype (parity: Cast, elemwise_unary_op.cc)."""
    from ..base import canonical_dtype

    return x.astype(canonical_dtype(dtype))


@register("amp_cast")
def _amp_cast(x, dtype="bfloat16"):
    """AMP-inserted cast (identity gradient; parity: amp_cast,
    amp_cast.cc)."""
    from ..base import canonical_dtype

    return x.astype(canonical_dtype(dtype))


# --------------------------------------------------------------- binary ----

def _samedim(fn):
    def wrapped(lhs, rhs):
        if lhs.shape != rhs.shape:
            raise ValueError(
                f"elemwise op requires identical shapes, got {lhs.shape} vs "
                f"{rhs.shape}; use the broadcast_* variant")
        return fn(lhs, rhs)

    return wrapped


_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "arctan2": jnp.arctan2,
}

for _name, _fn in _BINARY.items():
    register(f"elemwise_{_name}", aliases=(f"_{_name}",))(_samedim(_fn))
    register(f"broadcast_{_name}")(_fn)

register("broadcast_like")(lambda x, like: jnp.broadcast_to(x, like.shape))
register("broadcast_to")(lambda x, shape=(): jnp.broadcast_to(x, tuple(shape)))
register("broadcast_axis")(
    lambda x, axis=(), size=(): jnp.broadcast_to(
        x,
        tuple(
            (size[list(axis).index(i)] if i in tuple(axis) else s)
            for i, s in enumerate(x.shape)
        ),
    )
)


# --------------------------------------------------------------- scalar ----

_SCALAR = {
    "_plus_scalar": lambda x, scalar=0.0: x + scalar,
    "_minus_scalar": lambda x, scalar=0.0: x - scalar,
    "_rminus_scalar": lambda x, scalar=0.0: scalar - x,
    "_mul_scalar": lambda x, scalar=1.0: x * scalar,
    "_div_scalar": lambda x, scalar=1.0: x / scalar,
    "_rdiv_scalar": lambda x, scalar=1.0: scalar / x,
    "_mod_scalar": lambda x, scalar=1.0: jnp.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar=1.0: jnp.mod(scalar, x),
    "_power_scalar": lambda x, scalar=1.0: jnp.power(x, scalar),
    "_rpower_scalar": lambda x, scalar=1.0: jnp.power(scalar, x),
    "_maximum_scalar": lambda x, scalar=0.0: jnp.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar=0.0: jnp.minimum(x, scalar),
    "_equal_scalar": lambda x, scalar=0.0: (x == scalar).astype(x.dtype),
    "_not_equal_scalar": lambda x, scalar=0.0: (x != scalar).astype(x.dtype),
    "_greater_scalar": lambda x, scalar=0.0: (x > scalar).astype(x.dtype),
    "_greater_equal_scalar": lambda x, scalar=0.0: (x >= scalar).astype(x.dtype),
    "_lesser_scalar": lambda x, scalar=0.0: (x < scalar).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, scalar=0.0: (x <= scalar).astype(x.dtype),
}

for _name, _fn in _SCALAR.items():
    register(_name)(_fn)
